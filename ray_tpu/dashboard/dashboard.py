"""Dashboard: HTTP observability endpoint for a running cluster.

Reference parity: python/ray/dashboard/ (aiohttp app serving cluster
state, jobs, metrics to the UI) — collapsed to a threaded stdlib HTTP
server over the head's live registries:

  GET /                 tiny auto-refreshing HTML overview
  GET /api/cluster      `ray status`-shaped summary
  GET /api/nodes        node table
  GET /api/actors       actor table
  GET /api/tasks        task-state summary
  GET /api/pgs          placement groups
  GET /api/jobs         submitted jobs
  GET /api/objects      object store stats
  GET /metrics          Prometheus text exposition

    from ray_tpu.dashboard import start_dashboard
    dash = start_dashboard(port=8265)   # 0 = ephemeral port
    dash.url
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

_PAGE = """<!doctype html>
<html><head><title>ray_tpu dashboard</title>
<style>
 body{font-family:system-ui,sans-serif;margin:2rem;background:#fafafa;color:#222}
 h1{font-size:1.3rem} h2{font-size:1.05rem;margin-top:1.5rem}
 table{border-collapse:collapse;margin-top:.5rem} td,th{border:1px solid #ddd;padding:.3rem .6rem;font-size:.85rem;text-align:left}
 code{background:#eee;padding:0 .3rem}
</style></head>
<body>
<h1>ray_tpu dashboard</h1>
<div id="summary"></div>
<h2>Nodes</h2><table id="nodes"></table>
<h2>Actors</h2><table id="actors"></table>
<h2>Jobs</h2><table id="jobs"></table>
<script>
async function j(p){const r=await fetch(p);return r.json()}
function esc(v){const d=document.createElement('div');d.textContent=String(v);return d.innerHTML}
function row(cells,tag){return '<tr>'+cells.map(c=>`<${tag}>${esc(c)}</${tag}>`).join('')+'</tr>'}
function fill(id, header, rows){
  document.getElementById(id).innerHTML = row(header,'th') + rows.map(r=>row(r,'td')).join('')
}
async function refresh(){
  const c = await j('/api/cluster');
  document.getElementById('summary').innerHTML =
    `<p>Cluster: <code>${esc(JSON.stringify(c.cluster_resources))}</code> ·
      available <code>${esc(JSON.stringify(c.available_resources))}</code> ·
      pending demand: ${c.pending_demand.length}</p>`;
  fill('nodes', ['node','alive','workers','total','available'],
    c.nodes.map(n=>[n.node_id.slice(0,12), n.alive, n.num_workers,
                    JSON.stringify(n.resources), JSON.stringify(n.available)]));
  const a = await j('/api/actors');
  fill('actors', ['actor','name','state','class','restarts'],
    a.map(x=>[x.actor_id.slice(0,12), x.name||'', x.state, x['class'], x.num_restarts]));
  const jobs = await j('/api/jobs');
  fill('jobs', ['job','status','entrypoint','returncode'],
    jobs.map(x=>[x.job_id, x.status, x.entrypoint, x.returncode ?? '']));
}
refresh(); setInterval(refresh, 2000);
</script></body></html>"""


class Dashboard:
    def __init__(self, client=None, host: str = "127.0.0.1", port: int = 8265):
        from ray_tpu.core import context

        self.client = client or context.get_client()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # silence request logging
                pass

            def _send(self, body: bytes, ctype: str, code: int = 200):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _json(self, obj, code: int = 200):
                self._send(json.dumps(obj, default=str).encode(), "application/json", code)

            def do_GET(self):
                c = outer.client
                try:
                    path = self.path.split("?")[0].rstrip("/") or "/"
                    if path == "/":
                        self._send(_PAGE.encode(), "text/html")
                    elif path == "/api/cluster":
                        from ray_tpu.util.state import cluster_status

                        self._json(cluster_status(c))
                    elif path == "/api/nodes":
                        self._json(c.cluster_info("nodes"))
                    elif path == "/api/actors":
                        self._json(c.cluster_info("actors"))
                    elif path == "/api/tasks":
                        self._json(c.cluster_info("tasks"))
                    elif path == "/api/pgs":
                        self._json(c.cluster_info("placement_groups"))
                    elif path == "/api/objects":
                        self._json(c.cluster_info("objects"))
                    elif path == "/api/jobs":
                        from dataclasses import asdict

                        from ray_tpu.job.job_manager import _default_manager

                        jobs = _default_manager.list_jobs() if _default_manager else []
                        self._json([asdict(j) for j in jobs])
                    elif path.startswith("/api/stacks"):
                        # on-demand live stacks of (all|prefix) workers —
                        # the py-spy-attach capability (reference:
                        # dashboard/modules/reporter/profile_manager.py)
                        prefix = path[len("/api/stacks"):].strip("/")
                        self._json(c.dump_worker_stacks(prefix))
                    elif path == "/metrics":
                        from ray_tpu.util.metrics import export_prometheus

                        self._send(export_prometheus(c).encode(), "text/plain; version=0.0.4")
                    else:
                        self._json({"error": "not found"}, 404)
                except Exception as e:  # noqa: BLE001
                    self._json({"error": str(e)}, 500)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.host = host
        self.port = self._server.server_address[1]
        self._thread: threading.Thread | None = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def start(self):
        self._thread = threading.Thread(target=self._server.serve_forever, daemon=True, name="rt-dashboard")
        self._thread.start()
        return self

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


def start_dashboard(port: int = 8265, host: str = "127.0.0.1", client=None) -> Dashboard:
    return Dashboard(client=client, host=host, port=port).start()
