"""Grafana dashboard provisioning from the live metrics registry.

Reference parity: python/ray/dashboard/modules/metrics/
grafana_dashboard_factory.py:1 (generates the default Grafana dashboard
JSON served by `ray metrics launch-prometheus` tooling). Here the panel
set is DERIVED from the cluster's actual metric registry (util/metrics)
plus the standard core series, so user-defined Counters/Gauges/
Histograms get panels without editing any template.

    from ray_tpu.dashboard.grafana import grafana_dashboard_json
    open("ray_tpu_dashboard.json", "w").write(grafana_dashboard_json())

Point Grafana's dashboard provisioning at the emitted file; the panels
query the Prometheus datasource named by ``datasource`` scraping the
head's /metrics endpoint (dashboard/dashboard.py).
"""

from __future__ import annotations

import json

# the hand-built core panels' series (refreshed per scrape by
# util/metrics.update_core_metrics); scripts/lint_gate.py's dashboard
# smoke checks every panel expr against CORE_SERIES + the serving
# telemetry catalog + the live registry
CORE_SERIES = (
    "rt_tasks_finished_total",
    "rt_tasks_submitted_total",
    "rt_tasks_running",
    "rt_tasks_pending",
    "rt_object_store_bytes",
    "rt_object_store_spilled_bytes",
    "rt_transfer_pull_bytes_total",
    "rt_transfer_serve_bytes_total",
)


def _panel(pid: int, title: str, exprs: list[tuple[str, str]], *, y: int, x: int = 0, w: int = 12, h: int = 8, unit: str = "short", datasource: str = "Prometheus") -> dict:
    return {
        "id": pid,
        "title": title,
        "type": "timeseries",
        "datasource": {"type": "prometheus", "uid": datasource},
        "gridPos": {"x": x, "y": y, "w": w, "h": h},
        "fieldConfig": {"defaults": {"unit": unit}, "overrides": []},
        "targets": [
            {"expr": expr, "legendFormat": legend, "refId": chr(ord("A") + i)}
            for i, (expr, legend) in enumerate(exprs)
        ],
    }


def grafana_dashboard_json(client=None, *, datasource: str = "Prometheus", title: str = "ray_tpu") -> str:
    """Build the dashboard JSON: core panels (tasks, objects, transfers)
    plus one panel per registered application metric."""
    from ray_tpu.util.metrics import get_metrics_snapshot

    panels = []
    pid = 1
    y = 0

    def add(title, exprs, **kw):
        nonlocal pid, y
        panels.append(_panel(pid, title, exprs, y=y, datasource=datasource, **kw))
        pid += 1
        if kw.get("x", 0) + kw.get("w", 12) >= 24:
            y += kw.get("h", 8)

    # -- core panels (series from the head's /metrics exposition) --
    add("Task throughput", [("rate(rt_tasks_finished_total[1m])", "finished/s"), ("rate(rt_tasks_submitted_total[1m])", "submitted/s")], w=12, x=0)
    add("Tasks in flight", [("rt_tasks_running", "running"), ("rt_tasks_pending", "pending")], w=12, x=12)
    add("Object store", [("rt_object_store_bytes", "shm bytes"), ("rt_object_store_spilled_bytes", "spilled")], unit="bytes", w=12, x=0)
    add("Object transfers", [("rate(rt_transfer_pull_bytes_total[1m])", "pull B/s"), ("rate(rt_transfer_serve_bytes_total[1m])", "serve B/s")], unit="Bps", w=12, x=12)

    # -- Serving row: the LLM hot path's SLOs (llm/telemetry.py catalog;
    # series tagged by model/replica/stage, so legends stay per-replica) --
    add("Serving: time to first token", [
        ("histogram_quantile(0.5, rate(rt_llm_ttft_s_bucket[5m]))", "p50"),
        ("histogram_quantile(0.99, rate(rt_llm_ttft_s_bucket[5m]))", "p99"),
    ], unit="s", w=12, x=0)
    add("Serving: inter-token latency", [
        ("histogram_quantile(0.5, rate(rt_llm_itl_s_bucket[5m]))", "p50"),
        ("histogram_quantile(0.99, rate(rt_llm_itl_s_bucket[5m]))", "p99"),
    ], unit="s", w=12, x=12)
    add("Serving: admission queue", [
        ("histogram_quantile(0.99, rate(rt_llm_queue_wait_s_bucket[5m]))", "queue wait p99"),
        ("rt_llm_queue_depth", "depth"),
    ], w=12, x=0)
    add("Serving: token throughput", [
        ("rate(rt_llm_tokens_total[1m])", "decode tok/s"),
        ("rate(rt_llm_prefill_tokens_total[1m])", "prefill tok/s"),
    ], w=12, x=12)
    add("Serving: KV occupancy", [
        ("rt_llm_kv_occupancy", "occupied fraction"),
        ("rt_llm_slots_in_use", "slots in use"),
    ], w=12, x=0)
    add("Serving: KV HBM bytes", [("rt_llm_kv_hbm_bytes", "occupied bytes")], unit="bytes", w=12, x=12)
    add("Serving: speculation & preemption", [
        ("rt_llm_spec_acceptance", "spec acceptance"),
        ("rate(rt_llm_preemptions_total[5m])", "preemptions/s"),
    ], w=12, x=0)
    add("Serving: recompile sentinel", [
        ("increase(rt_llm_recompiles_total[5m])", "recompiles (5m)"),
    ], w=12, x=12)
    add("Serving: collective wire", [
        ("rate(rt_llm_collective_wire_bytes_total[1m])", "ICI B/s"),
    ], unit="Bps", w=12, x=0)
    add("Serving: disagg handoffs", [
        ("rate(rt_llm_handoff_bytes_total[1m])", "handoff B/s"),
        ("rate(rt_llm_handoffs_total[1m])", "events/s"),
    ], w=12, x=12)
    add("Serving: cluster prefix reuse", [
        # cluster hit-rate: hits (both tiers, all replicas) per admitted
        # request — shared-prefix traffic converging on warm replicas
        ("sum by (tier) (rate(rt_llm_prefix_hits_total[5m]))", "hits/s {{tier}}"),
        ("sum(rate(rt_llm_prefix_hits_total[5m])) / sum(rate(rt_llm_requests_finished_total[5m]))", "cluster hit-rate"),
        ("rate(rt_llm_prefix_fetch_bytes_total[1m])", "remote fetch B/s"),
    ], w=12, x=0)
    add("Serving: overload & drain", [
        # the degradation-order dashboard: under pressure the shed rate
        # (lowest class first) and queue-wait estimate move while decode
        # ITL (panel above) must not. `stage` stays in the sum because a
        # router's per-request sheds and the replica ingresses'
        # per-attempt sheds are different rates — folding them together
        # would overcount one client request by its failover fan-out
        ("sum by (class, stage) (rate(rt_llm_requests_shed_total[1m]))", "shed/s {{stage}} c{{class}}"),
        ("rt_llm_admission_queue_wait_est_ms", "est queue wait (ms)"),
        ("rt_llm_drain_state", "drain state"),
        ("rate(rt_llm_retry_budget_exhausted_total[5m])", "retry budget exhausted/s"),
    ], w=12, x=12)
    add("Serving: preemption & migration", [
        # the evacuation dashboard (llm/migrate.py): checkpoint/restore
        # rates by outcome (source and peer replicas count their own
        # halves; routers count resumed/lost once per client request —
        # stage separates them), splice latency p99, and the checkpoint
        # bytes crossing the object plane
        ("sum by (outcome, stage) (rate(rt_llm_migrations_total[5m]))", "migrations/s {{stage}} {{outcome}}"),
        ("histogram_quantile(0.99, sum by (le) (rate(rt_llm_migration_splice_s_bucket[5m])))", "splice p99 (s)"),
        ("rate(rt_llm_migration_bytes_total[1m])", "checkpoint B/s"),
    ], w=12, x=0)
    add("Serving: KV tiering", [
        # latency-hiding KV plane v2 (ROADMAP item 3): the async fetch
        # span p99 (transfers overlapping serving steps — compare against
        # the ITL panel: a healthy fleet's fetch p99 exceeding ITL is
        # FINE, that's the latency being hidden), the predictive
        # prefetcher's remote->local conversion rate, and the
        # conversation-KV spill volume leaving HBM for the DRAM tier
        ("histogram_quantile(0.99, sum by (le) (rate(rt_llm_prefix_fetch_overlap_s_bucket[5m])))", "async fetch p99 (s)"),
        ("rate(rt_llm_prefix_prefetch_hits_total[5m])", "prefetch-converted hits/s"),
        ("rate(rt_llm_kv_spilled_bytes_total[1m])", "KV spill B/s"),
    ], w=12, x=12)

    # -- one panel per registered metric (user Counters/Gauges/Histograms) --
    try:
        snapshot = get_metrics_snapshot(client)
    except Exception:
        snapshot = {}
    for name, m in sorted(snapshot.items()):
        if name.startswith("rt_"):
            continue  # core series already have hand-built panels above
        kind = m.get("kind", "gauge")
        if kind == "counter":
            exprs = [(f"rate({name}[1m])", f"{name}/s")]
        elif kind == "histogram":
            exprs = [
                (f"histogram_quantile(0.5, rate({name}_bucket[5m]))", "p50"),
                (f"histogram_quantile(0.99, rate({name}_bucket[5m]))", "p99"),
            ]
        else:
            exprs = [(name, name)]
        add(m.get("description") or name, exprs, w=12, x=(len(panels) % 2) * 12)

    dashboard = {
        "uid": "ray-tpu-default",
        "title": title,
        "tags": ["ray_tpu"],
        "timezone": "browser",
        "schemaVersion": 39,
        "refresh": "10s",
        "time": {"from": "now-30m", "to": "now"},
        "panels": panels,
    }
    return json.dumps(dashboard, indent=1)
