"""GKE/TPU node provider: slice-granular, atomic, label-aware.

Reference parity: python/ray/autoscaler/_private/gcp/node_provider.py:1
(GCP REST provisioning) + python/ray/autoscaler/batching_node_provider.py:1
(kuberay: desired-state patches against an API server, used for TPU
slices). TPU-first redesign: the provisioning unit is a whole SLICE
(one `create_tpu_node_pool` call brings up every host VM of the slice
atomically), never an individual VM — a partial slice cannot run an SPMD
program, so scaling by hosts is meaningless on TPU pods.

The REST surface is injected (``api``), with the exact method shapes a
GKE node-pool client exposes; production backs it with
container.googleapis.com + tpu.googleapis.com, tests with a fake that
"boots" agents into the cluster:

    api.create_tpu_node_pool(name, pod_type, labels) -> {"hosts": N}
    api.delete_tpu_node_pool(name)
    api.list_tpu_node_pools() -> {name: {...}}

Joined hosts carry the slice labels from accelerators/tpu.py
(ray_tpu.io/tpu-slice-name / -worker-id / -pod-type), per-host chips as
``TPU`` resources, and worker 0 the ``TPU-{pod}-head`` marker that gang
reservations (util/tpu.py SlicePlacementGroup) key on. The autoscaler
therefore scales SLICES whenever queued demand carries a head resource.
"""

from __future__ import annotations

import logging
import time
import uuid

from ray_tpu.autoscaler.autoscaler import NodeProvider, NodeTypeConfig

logger = logging.getLogger(__name__)

SLICE_LABEL = "ray_tpu.io/tpu-slice-name"
POD_TYPE_LABEL = "ray_tpu.io/tpu-pod-type"


def slice_node_type(pod_type: str, *, name: str | None = None, num_cpus_per_host: int = 8, max_slices: int = 4, min_slices: int = 0) -> NodeTypeConfig:
    """NodeTypeConfig describing ONE slice of ``pod_type`` as the scaling
    unit: resources are the slice AGGREGATE (so head-resource and whole-
    slice demand match in _pick_type), labels carry the pod type for the
    provider."""
    from ray_tpu.accelerators.tpu import chips_per_host, num_hosts

    hosts = num_hosts(pod_type)
    chips = chips_per_host(pod_type)
    return NodeTypeConfig(
        name=name or f"tpu-{pod_type}",
        resources={
            "CPU": float(num_cpus_per_host * hosts),
            "TPU": float(chips * hosts),
            f"TPU-{pod_type}-head": 1.0,
        },
        min_workers=min_slices,
        max_workers=max_slices,
        labels={POD_TYPE_LABEL: pod_type},
    )


class GKETPUNodeProvider(NodeProvider):
    """create_node provisions ONE whole slice; terminate_node tears the
    whole slice down. The autoscaler tracks the slice through its
    worker-0 node; ``nodes_in_group`` exposes the full membership for
    idle/busy accounting."""

    JOIN_TIMEOUT_S = 300.0

    def __init__(self, runtime, api):
        self.rt = runtime
        self.api = api
        self._slices: dict = {}  # slice_name -> [node_ids]

    def _slice_nodes(self, slice_name: str):
        return [n for n in self.rt.node_list() if n.labels.get(SLICE_LABEL) == slice_name]

    def create_node(self, node_type: NodeTypeConfig):
        pod_type = node_type.labels.get(POD_TYPE_LABEL)
        if not pod_type:
            raise ValueError(f"node type {node_type.name!r} has no {POD_TYPE_LABEL} label; use slice_node_type()")
        slice_name = f"{node_type.name}-{uuid.uuid4().hex[:6]}"
        info = self.api.create_tpu_node_pool(slice_name, pod_type, dict(node_type.labels))
        want_hosts = int(info.get("hosts", 0)) or 1
        deadline = time.monotonic() + self.JOIN_TIMEOUT_S
        while time.monotonic() < deadline:
            members = self._slice_nodes(slice_name)
            if len(members) >= want_hosts:
                members.sort(key=lambda n: int(n.labels.get("ray_tpu.io/tpu-worker-id", 0)))
                for n in members:
                    n.labels["ray_tpu.io/node-type"] = node_type.name
                self._slices[slice_name] = [n.node_id for n in members]
                logger.info("slice %s up: %d hosts of %s", slice_name, want_hosts, pod_type)
                return members[0]  # worker 0 represents the slice
            time.sleep(0.25)
        # partial slice is useless: roll the pool back
        try:
            self.api.delete_tpu_node_pool(slice_name)
        except Exception:
            pass
        raise TimeoutError(f"slice {slice_name} ({want_hosts} hosts) never fully joined")

    def terminate_node(self, node):
        slice_name = node.labels.get(SLICE_LABEL)
        if slice_name is None:
            self.rt.remove_node(node.node_id, graceful=True)
            return
        member_ids = self._slices.pop(slice_name, None) or [n.node_id for n in self._slice_nodes(slice_name)]
        for nid in member_ids:
            try:
                self.rt.remove_node(nid, graceful=True)
            except Exception:
                logger.warning("failed removing slice member %s", nid.hex()[:8])
        try:
            self.api.delete_tpu_node_pool(slice_name)
        except Exception:
            logger.exception("GKE delete of slice %s failed", slice_name)
        logger.info("slice %s terminated (%d hosts)", slice_name, len(member_ids))

    def nodes_in_group(self, node):
        """Every host of the node's slice (autoscaler busy/idle checks
        must consider the whole gang, not just worker 0)."""
        slice_name = node.labels.get(SLICE_LABEL)
        if slice_name is None:
            return [node]
        return self._slice_nodes(slice_name) or [node]
