"""YAML cluster launcher: `rt up cluster.yaml` / `rt down`.

Reference parity: `ray up`/`ray down` against a cluster YAML
(python/ray/autoscaler/_private/commands.py + the cluster config schema)
— reduced to the fields that matter here:

    cluster_name: demo
    head:
      num_cpus: 4            # head node resources
      node_manager_port: 0   # fixed port enables agent reconnect
      gcs_persist_path: ""   # non-empty enables head fault tolerance
    provider:
      type: command          # or "local"
      launch_command: >      # command provider: how to start one worker
        ssh {node_type}-pool 'rt agent --address {address}
        --authkey {authkey} --transfer-authkey {transfer_authkey}
        --num-cpus {num_cpus} --num-tpus {num_tpus}'
    available_node_types:
      cpu_worker:
        resources: {CPU: 4}
        min_workers: 1
        max_workers: 4

`up()` starts the head runtime in THIS process, brings up min_workers
per type, and runs the demand-driven autoscaler until stopped. `rt up`
runs it in the FOREGROUND (background with `rt up cfg.yaml &` / a
process manager) and records a pidfile so `rt down` can stop it.
"""

from __future__ import annotations

import os
import signal
import time

from ray_tpu.autoscaler.autoscaler import (
    Autoscaler,
    CommandNodeProvider,
    LocalNodeProvider,
    NodeTypeConfig,
)


def load_config(path: str) -> dict:
    import json

    with open(path) as f:
        text = f.read()
    if path.endswith(".json"):
        return json.loads(text)
    import yaml

    return yaml.safe_load(text)


def _node_types(cfg: dict) -> list[NodeTypeConfig]:
    out = []
    for name, spec in (cfg.get("available_node_types") or {}).items():
        out.append(
            NodeTypeConfig(
                name=name,
                resources=dict(spec.get("resources") or {"CPU": 1}),
                min_workers=int(spec.get("min_workers", 0)),
                max_workers=int(spec.get("max_workers", 10)),
                labels=dict(spec.get("labels") or {}),
            )
        )
    return out


def _provider(cfg: dict, runtime):
    p = cfg.get("provider") or {"type": "local"}
    kind = p.get("type", "local")
    if kind == "local":
        return LocalNodeProvider(runtime)
    if kind == "command":
        return CommandNodeProvider(runtime, p["launch_command"], p.get("terminate_command"))
    raise ValueError(f"unknown provider type {kind!r} (local | command)")


class Cluster:
    """A launched cluster: head runtime + autoscaler + providers."""

    def __init__(self, config: dict):
        import ray_tpu
        from ray_tpu.core import context

        self.config = config
        head = config.get("head") or {}
        system_config = {}
        if head.get("node_manager_port"):
            system_config["node_manager_port"] = int(head["node_manager_port"])
        if head.get("gcs_persist_path"):
            system_config["gcs_persist_path"] = head["gcs_persist_path"]
        if head.get("node_manager_host"):
            # cross-host workers must dial a routable head address, not the
            # loopback default (e.g. 0.0.0.0 bind + the head's LAN IP)
            system_config["node_manager_host"] = head["node_manager_host"]
        ray_tpu.init(num_cpus=int(head.get("num_cpus", os.cpu_count() or 4)), _system_config=system_config or None)
        self.runtime = context.get_client()
        self.node_types = _node_types(config)
        self.provider = _provider(config, self.runtime)
        self.autoscaler = Autoscaler(self.runtime, self.node_types, provider=self.provider)
        # bring up the floor before demand-driven scaling takes over —
        # and ADOPT each node so reconcile counts it toward min_workers
        # rather than launching the floor a second time
        for nt in self.node_types:
            for _ in range(nt.min_workers):
                self.autoscaler.adopt(self.provider.create_node(nt), nt.name)
        self.autoscaler.start()

    def wait(self):
        """Block until SIGTERM/SIGINT (the `rt up` foreground loop)."""
        stop = []
        signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
        try:
            while not stop:
                time.sleep(0.5)
        except KeyboardInterrupt:
            pass

    def shutdown(self):
        import ray_tpu

        self.autoscaler.stop()
        ray_tpu.shutdown()


def up(config_path: str, block: bool = True) -> Cluster:
    cluster = Cluster(load_config(config_path))
    from ray_tpu.util.state import session_dir

    with open(os.path.join(session_dir(), "cluster.pid"), "w") as f:
        f.write(str(os.getpid()))
    if block:
        try:
            cluster.wait()
        finally:
            cluster.shutdown()
    return cluster


def down() -> bool:
    """Stop the newest LIVE `rt up` head (SIGTERM via its pidfile). Dead
    pidfiles are cleaned up and skipped; a recycled pid is rejected by a
    /proc cmdline check (the process must still be a python head)."""
    from ray_tpu.util.state import session_dir

    root = os.path.dirname(session_dir())
    candidates = []
    try:
        sessions = os.listdir(root)
    except FileNotFoundError:
        return False
    for s in sessions:
        p = os.path.join(root, s, "cluster.pid")
        try:
            ts = os.path.getmtime(p)
        except OSError:
            continue
        candidates.append((ts, p))
    for _, p in sorted(candidates, reverse=True):
        try:
            with open(p) as f:
                pid = int(f.read().strip())
        except (OSError, ValueError):
            continue
        # liveness + identity: the pid must still be the session owner
        # (dir is named after the head's own pid), still alive, and still
        # a python process — a recycled pid fails the cmdline check
        if f"session_{pid}" not in p:
            continue
        alive = True
        try:
            os.kill(pid, 0)
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmdline = f.read()
            if b"python" not in cmdline and b"rt" not in cmdline:
                alive = False
        except (ProcessLookupError, PermissionError, OSError):
            alive = False
        if not alive:
            try:
                os.unlink(p)  # stale: clean up so it can't shadow anything
            except OSError:
                pass
            continue
        os.kill(pid, signal.SIGTERM)
        return True
    return False
