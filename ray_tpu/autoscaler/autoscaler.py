"""Autoscaler: demand-driven reconciliation of cluster membership.

Reference parity: python/ray/autoscaler/v2/autoscaler.py:47 (Autoscaler —
one reconcile pass per tick over instance manager state) and
instance_manager.py:29 (declarative instance lifecycle). Re-shaped for the
single-host control plane: the "cloud" is a NodeProvider; the default
LocalNodeProvider launches real node-agent daemon processes
(core/node_agent.py), so scale-up/down exercises true process boundaries.

Reconcile pass (v2 semantics, collapsed):
1. read demand: resource requests of queued-but-unplaced tasks
   (scheduler.pending_demand()) + min_workers floors,
2. bin-pack demand onto (alive nodes' headroom + already-pending
   launches); whatever does not fit produces launches of the first node
   type that satisfies the request, bounded by max_workers,
3. terminate autoscaler-launched nodes idle (no busy workers, no PG
   bundles) longer than idle_timeout_s.

`status()` renders the `ray status`-style summary.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field

logger = logging.getLogger(__name__)


@dataclass
class NodeTypeConfig:
    name: str
    resources: dict
    min_workers: int = 0
    max_workers: int = 10
    labels: dict = field(default_factory=dict)


class NodeProvider:
    """Cloud abstraction (reference: autoscaler node provider interface)."""

    def create_node(self, node_type: NodeTypeConfig):
        raise NotImplementedError

    def terminate_node(self, node):
        raise NotImplementedError


class LocalNodeProvider(NodeProvider):
    """Launch node-agent daemon processes on this machine."""

    def __init__(self, runtime):
        self.rt = runtime

    def create_node(self, node_type: NodeTypeConfig):
        return self.rt.add_node(
            dict(node_type.resources),
            labels={**node_type.labels, "ray_tpu.io/node-type": node_type.name},
        )

    def terminate_node(self, node):
        self.rt.remove_node(node.node_id, graceful=True)


class CommandNodeProvider(NodeProvider):
    """Launch nodes by running a shell command that starts an `rt agent`
    somewhere — ssh to another machine, a cloud CLI creating a VM whose
    startup script joins, or a local subprocess in tests. This is the
    cloud-provider seam (reference: the autoscaler's NodeProvider
    implementations — node_provider.py subclasses wrap clouds the same
    way: run something that makes a raylet join the head).

    launch_command is a format string receiving {address} {authkey}
    {transfer_authkey} {num_cpus} {num_tpus} {node_type}; the started
    agent dials the head's AgentListener, and create_node returns once
    the joined node appears (or raises on timeout)."""

    JOIN_TIMEOUT_S = 120.0

    def __init__(self, runtime, launch_command: str, terminate_command: str | None = None):
        self.rt = runtime
        self.launch_command = launch_command
        self.terminate_command = terminate_command
        self._procs: dict = {}  # node_id -> subprocess handle

    def _known_joined(self) -> set:
        return {n.node_id for n in self.rt.node_list() if n.labels.get("ray_tpu.io/node-type") == "joined"}

    def create_node(self, node_type: NodeTypeConfig):
        import subprocess
        import uuid

        host, port = self.rt._agent_listener.address
        # include "--join-token {join_token}" in launch_command for EXACT
        # launch<->node matching; without it, adoption falls back to a
        # capacity check (a concurrent operator-run join could be claimed)
        token = uuid.uuid4().hex[:12]
        use_token = "{join_token}" in self.launch_command
        cmd = self.launch_command.format(
            address=f"{host}:{port}",
            authkey=self.rt._agent_listener.authkey.hex(),
            transfer_authkey=self.rt._transfer_authkey.hex(),
            num_cpus=node_type.resources.get("CPU", 1),
            num_tpus=node_type.resources.get("TPU", 0),
            node_type=node_type.name,
            join_token=token,
        )
        before = self._known_joined()
        proc = subprocess.Popen(cmd, shell=True)  # operator-authored shell line (ssh, pipes, ...)
        deadline = time.monotonic() + self.JOIN_TIMEOUT_S
        want = node_type.resources
        while time.monotonic() < deadline:
            for node_id in self._known_joined() - before:
                with self.rt._nodes_lock:
                    node = self.rt.nodes.get(node_id)
                if node is None:
                    continue  # joined and died in the window
                if use_token:
                    if node.labels.get("ray_tpu.io/join-token") != token:
                        continue
                elif any(node.total_resources.get(k, 0) < v for k, v in want.items() if v > 0):
                    continue
                node.labels["ray_tpu.io/node-type"] = node_type.name
                self._procs[node_id] = proc
                return node
            if proc.poll() is not None and proc.returncode != 0:
                raise RuntimeError(f"launch command exited {proc.returncode}: {cmd}")
            time.sleep(0.25)
        proc.terminate()
        raise TimeoutError(f"node from {node_type.name!r} never joined within {self.JOIN_TIMEOUT_S}s")

    def terminate_node(self, node):
        import subprocess

        node_type = node.labels.get("ray_tpu.io/node-type", "")
        self.rt.remove_node(node.node_id, graceful=True)
        proc = self._procs.pop(node.node_id, None)
        if proc is not None and proc.poll() is None:
            proc.terminate()
        if self.terminate_command:

            class _Safe(dict):
                def __missing__(self, key):  # unknown placeholder: keep literal
                    return "{" + key + "}"

            cmd = self.terminate_command.format_map(
                _Safe(node_id=node.node_id.hex(), node_type=node_type)
            )
            try:
                subprocess.Popen(cmd, shell=True)
            except OSError as e:
                logger.warning("terminate command failed to start: %s (%s)", cmd, e)


def _fits(avail: dict, req: dict) -> bool:
    return all(avail.get(k, 0.0) >= v - 1e-9 for k, v in req.items() if v > 0)


def _take(avail: dict, req: dict):
    for k, v in req.items():
        if v > 0:
            avail[k] = avail.get(k, 0.0) - v


class Autoscaler:
    def __init__(
        self,
        runtime,
        node_types: list[NodeTypeConfig],
        *,
        provider: NodeProvider | None = None,
        idle_timeout_s: float = 60.0,
        interval_s: float = 1.0,
        upscaling_speed: int = 4,
    ):
        self.rt = runtime
        self.node_types = {t.name: t for t in node_types}
        self.provider = provider or LocalNodeProvider(runtime)
        self.idle_timeout_s = idle_timeout_s
        self.interval_s = interval_s
        self.upscaling_speed = max(1, upscaling_speed)
        self._managed: dict = {}  # node_id -> (type_name, launched_at)
        self._idle_since: dict = {}  # node_id -> ts
        self._launching: dict = {}  # type_name -> in-flight launch count
        self._stopped = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()

    # -- lifecycle --
    def adopt(self, node, type_name: str):
        """Register an externally-launched node as managed (the launcher's
        min_workers floor) so reconcile counts it toward the type's floor
        instead of double-launching."""
        with self._lock:
            self._managed[node.node_id] = (type_name, time.monotonic())

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True, name="rt-autoscaler")
        self._thread.start()
        return self

    def stop(self):
        self._stopped.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def _loop(self):
        while not self._stopped.wait(self.interval_s):
            try:
                self.reconcile()
            except Exception:
                logger.exception("autoscaler reconcile failed")

    # -- one reconcile pass --
    def reconcile(self):
        with self._lock:
            nodes = self.rt.node_list()
            alive_ids = {n.node_id for n in nodes}
            self._managed = {nid: v for nid, v in self._managed.items() if nid in alive_ids}

            counts: dict[str, int] = {t: 0 for t in self.node_types}
            for nid, (tname, _) in self._managed.items():
                counts[tname] = counts.get(tname, 0) + 1
            # launches dispatched to threads but not yet joined count too,
            # or every reconcile tick would double-launch a slow provider
            for tname, n in self._launching.items():
                counts[tname] = counts.get(tname, 0) + n

            # demand = queued tasks + pending placement groups (gang/slice
            # reservations surface here, e.g. TPU-{pod}-head) + floors
            demand = self.rt.scheduler.pending_demand()
            if hasattr(self.rt, "pending_pg_demand"):
                demand = demand + self.rt.pending_pg_demand()
            headroom = [dict(n.available) for n in nodes]
            launches: list[NodeTypeConfig] = []
            # capacity already being launched counts as planned headroom:
            # demand that an in-flight (async) launch will satisfy must
            # not provision AGAIN on the next reconcile tick
            planned: list[dict] = [
                dict(self.node_types[tname].resources)
                for tname, n in self._launching.items()
                if tname in self.node_types
                for _ in range(n)
            ]

            def try_place(req: dict) -> bool:
                for h in headroom + planned:
                    if _fits(h, req):
                        _take(h, req)
                        return True
                return False

            for req in demand:
                if not req or try_place(req):
                    continue
                t = self._pick_type(req, counts)
                if t is None:
                    continue  # infeasible on every configured type (or maxed)
                counts[t.name] += 1
                launches.append(t)
                h = dict(t.resources)
                _take(h, req)
                planned.append(h)

            for t in self.node_types.values():
                while counts.get(t.name, 0) < t.min_workers:
                    counts[t.name] += 1
                    launches.append(t)
                    planned.append(dict(t.resources))

            to_launch = launches[: self.upscaling_speed]

        # launch on DETACHED threads: a cloud provider can take minutes
        # per node/slice (VM boot, GKE node-pool creation), and one slow
        # create must not stall other scaling decisions, idle teardown,
        # or the reconcile loop itself (reference: the autoscaler's
        # concurrent NodeLauncher workers)
        def _launch(t: NodeTypeConfig):
            node = None
            try:
                node = self.provider.create_node(t)
            except Exception as e:  # noqa: BLE001
                logger.warning("autoscaler launch of %s failed: %s", t.name, e)
            # one lock section: the in-flight count converts to a managed
            # entry atomically, so no reconcile pass sees neither
            with self._lock:
                self._launching[t.name] = max(0, self._launching.get(t.name, 0) - 1)
                if node is not None:
                    self._managed[node.node_id] = (t.name, time.monotonic())
            if node is not None:
                logger.info("autoscaler launched node %s type=%s", node.node_id.hex()[:8], t.name)

        for t in to_launch:
            if self._stopped.is_set():
                return
            with self._lock:
                self._launching[t.name] = self._launching.get(t.name, 0) + 1
            threading.Thread(target=_launch, args=(t,), daemon=True, name="rt-launch").start()

        with self._lock:
            nodes = self.rt.node_list()
            # scale-down: managed nodes idle past the timeout, above min
            now = time.monotonic()
            for n in nodes:
                entry = self._managed.get(n.node_id)
                if entry is None:
                    continue
                tname, _ = entry
                # group-aware: a slice is busy if ANY of its hosts is
                # (the provider groups gang members, gke.nodes_in_group)
                group = getattr(self.provider, "nodes_in_group", lambda x: [x])(n)
                busy = any(
                    any(w.state in ("busy", "actor", "starting") for w in g.workers.values())
                    or bool(g.pg_bundles)
                    or bool(g.dispatch_queue)
                    for g in group
                )
                if busy:
                    self._idle_since.pop(n.node_id, None)
                    continue
                first_idle = self._idle_since.setdefault(n.node_id, now)
                if now - first_idle >= self.idle_timeout_s and counts.get(tname, 0) > self.node_types[tname].min_workers:
                    counts[tname] -= 1
                    self._managed.pop(n.node_id, None)
                    self._idle_since.pop(n.node_id, None)
                    logger.info("autoscaler terminating idle node %s", n.node_id.hex()[:8])
                    self.provider.terminate_node(n)

    def _pick_type(self, req: dict, counts: dict) -> NodeTypeConfig | None:
        for t in self.node_types.values():
            if counts.get(t.name, 0) >= t.max_workers:
                continue
            if _fits(dict(t.resources), req):
                return t
        return None

    # -- observability --
    def status(self) -> dict:
        with self._lock:
            nodes = self.rt.node_list()
            return {
                "nodes": [
                    {
                        "node_id": n.node_id.hex(),
                        "type": self._managed.get(n.node_id, ("head/static",))[0],
                        "resources": dict(n.total_resources),
                        "available": dict(n.available),
                    }
                    for n in nodes
                ],
                "pending_demand": self.rt.scheduler.pending_demand(),
                "managed_count": len(self._managed),
            }
