"""Autoscaler: demand-driven reconciliation of cluster membership.

Reference parity: python/ray/autoscaler/v2/autoscaler.py:47 (Autoscaler —
one reconcile pass per tick over instance manager state) and
instance_manager.py:29 (declarative instance lifecycle). Re-shaped for the
single-host control plane: the "cloud" is a NodeProvider; the default
LocalNodeProvider launches real node-agent daemon processes
(core/node_agent.py), so scale-up/down exercises true process boundaries.

Reconcile pass (v2 semantics, collapsed):
1. read demand: resource requests of queued-but-unplaced tasks
   (scheduler.pending_demand()) + min_workers floors,
2. bin-pack demand onto (alive nodes' headroom + already-pending
   launches); whatever does not fit produces launches of the first node
   type that satisfies the request, bounded by max_workers,
3. terminate autoscaler-launched nodes idle (no busy workers, no PG
   bundles) longer than idle_timeout_s.

`status()` renders the `ray status`-style summary.
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field

logger = logging.getLogger(__name__)


@dataclass
class NodeTypeConfig:
    name: str
    resources: dict
    min_workers: int = 0
    max_workers: int = 10
    labels: dict = field(default_factory=dict)


class NodeProvider:
    """Cloud abstraction (reference: autoscaler node provider interface)."""

    def create_node(self, node_type: NodeTypeConfig):
        raise NotImplementedError

    def terminate_node(self, node):
        raise NotImplementedError


class LocalNodeProvider(NodeProvider):
    """Launch node-agent daemon processes on this machine."""

    def __init__(self, runtime):
        self.rt = runtime

    def create_node(self, node_type: NodeTypeConfig):
        return self.rt.add_node(
            dict(node_type.resources),
            labels={**node_type.labels, "ray_tpu.io/node-type": node_type.name},
        )

    def terminate_node(self, node):
        self.rt.remove_node(node.node_id, graceful=True)


def _fits(avail: dict, req: dict) -> bool:
    return all(avail.get(k, 0.0) >= v - 1e-9 for k, v in req.items() if v > 0)


def _take(avail: dict, req: dict):
    for k, v in req.items():
        if v > 0:
            avail[k] = avail.get(k, 0.0) - v


class Autoscaler:
    def __init__(
        self,
        runtime,
        node_types: list[NodeTypeConfig],
        *,
        provider: NodeProvider | None = None,
        idle_timeout_s: float = 60.0,
        interval_s: float = 1.0,
        upscaling_speed: int = 4,
    ):
        self.rt = runtime
        self.node_types = {t.name: t for t in node_types}
        self.provider = provider or LocalNodeProvider(runtime)
        self.idle_timeout_s = idle_timeout_s
        self.interval_s = interval_s
        self.upscaling_speed = max(1, upscaling_speed)
        self._managed: dict = {}  # node_id -> (type_name, launched_at)
        self._idle_since: dict = {}  # node_id -> ts
        self._stopped = threading.Event()
        self._thread: threading.Thread | None = None
        self._lock = threading.Lock()

    # -- lifecycle --
    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True, name="rt-autoscaler")
        self._thread.start()
        return self

    def stop(self):
        self._stopped.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def _loop(self):
        while not self._stopped.wait(self.interval_s):
            try:
                self.reconcile()
            except Exception:
                logger.exception("autoscaler reconcile failed")

    # -- one reconcile pass --
    def reconcile(self):
        with self._lock:
            nodes = self.rt.node_list()
            alive_ids = {n.node_id for n in nodes}
            self._managed = {nid: v for nid, v in self._managed.items() if nid in alive_ids}

            counts: dict[str, int] = {t: 0 for t in self.node_types}
            for nid, (tname, _) in self._managed.items():
                counts[tname] = counts.get(tname, 0) + 1

            # demand = queued tasks + min_workers floors
            demand = self.rt.scheduler.pending_demand()
            headroom = [dict(n.available) for n in nodes]
            launches: list[NodeTypeConfig] = []
            planned: list[dict] = []

            def try_place(req: dict) -> bool:
                for h in headroom + planned:
                    if _fits(h, req):
                        _take(h, req)
                        return True
                return False

            for req in demand:
                if not req or try_place(req):
                    continue
                t = self._pick_type(req, counts)
                if t is None:
                    continue  # infeasible on every configured type (or maxed)
                counts[t.name] += 1
                launches.append(t)
                h = dict(t.resources)
                _take(h, req)
                planned.append(h)

            for t in self.node_types.values():
                while counts.get(t.name, 0) < t.min_workers:
                    counts[t.name] += 1
                    launches.append(t)
                    planned.append(dict(t.resources))

            for t in launches[: self.upscaling_speed]:
                node = self.provider.create_node(t)
                self._managed[node.node_id] = (t.name, time.monotonic())
                logger.info("autoscaler launched node %s type=%s", node.node_id.hex()[:8], t.name)

            # scale-down: managed nodes idle past the timeout, above min
            now = time.monotonic()
            for n in nodes:
                entry = self._managed.get(n.node_id)
                if entry is None:
                    continue
                tname, _ = entry
                busy = any(w.state in ("busy", "actor", "starting") for w in n.workers.values()) or bool(
                    n.pg_bundles
                ) or bool(n.dispatch_queue)
                if busy:
                    self._idle_since.pop(n.node_id, None)
                    continue
                first_idle = self._idle_since.setdefault(n.node_id, now)
                if now - first_idle >= self.idle_timeout_s and counts.get(tname, 0) > self.node_types[tname].min_workers:
                    counts[tname] -= 1
                    self._managed.pop(n.node_id, None)
                    self._idle_since.pop(n.node_id, None)
                    logger.info("autoscaler terminating idle node %s", n.node_id.hex()[:8])
                    self.provider.terminate_node(n)

    def _pick_type(self, req: dict, counts: dict) -> NodeTypeConfig | None:
        for t in self.node_types.values():
            if counts.get(t.name, 0) >= t.max_workers:
                continue
            if _fits(dict(t.resources), req):
                return t
        return None

    # -- observability --
    def status(self) -> dict:
        with self._lock:
            nodes = self.rt.node_list()
            return {
                "nodes": [
                    {
                        "node_id": n.node_id.hex(),
                        "type": self._managed.get(n.node_id, ("head/static",))[0],
                        "resources": dict(n.total_resources),
                        "available": dict(n.available),
                    }
                    for n in nodes
                ],
                "pending_demand": self.rt.scheduler.pending_demand(),
                "managed_count": len(self._managed),
            }
