from ray_tpu.autoscaler.autoscaler import (
    Autoscaler,
    CommandNodeProvider,
    LocalNodeProvider,
    NodeProvider,
    NodeTypeConfig,
)
from ray_tpu.autoscaler.launcher import Cluster as LaunchedCluster
from ray_tpu.autoscaler.launcher import down, load_config, up

__all__ = [
    "Autoscaler",
    "CommandNodeProvider",
    "LaunchedCluster",
    "LocalNodeProvider",
    "NodeProvider",
    "NodeTypeConfig",
    "down",
    "load_config",
    "up",
]
