from ray_tpu.autoscaler.autoscaler import (
    Autoscaler,
    LocalNodeProvider,
    NodeProvider,
    NodeTypeConfig,
)

__all__ = ["Autoscaler", "LocalNodeProvider", "NodeProvider", "NodeTypeConfig"]
