"""ray_tpu.rllib: reinforcement-learning library, TPU-native.

Same topology as the reference RLlib (rllib/algorithms/algorithm.py:212 —
Algorithm over an EnvRunnerGroup of rollout actors and a LearnerGroup of
update actors) with the torch/DDP learner stack replaced by pure-JAX
functional modules and jitted optax updates; multi-learner gradient sync
rides ray_tpu.collective (host allreduce) or a GSPMD mesh instead of NCCL.

Public surface:
  - AlgorithmConfig builders (`PPOConfig`, `APPOConfig`, `IMPALAConfig`,
    `DQNConfig`, `SACConfig`, `BCConfig`, `CQLConfig`, `MARWILConfig`)
  - `config.build()` -> Algorithm; `algo.train()` -> result dict
  - RLModule: functional JAX policy/value modules
"""

from ray_tpu.util.usage import record_library_usage as _rlu

_rlu("rllib")

from ray_tpu.rllib.algorithms.algorithm import Algorithm  # noqa: F401
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig  # noqa: F401
from ray_tpu.rllib.algorithms.appo.appo import APPO, APPOConfig  # noqa: F401
from ray_tpu.rllib.algorithms.bc.bc import BC, BCConfig  # noqa: F401
from ray_tpu.rllib.algorithms.cql.cql import CQL, CQLConfig  # noqa: F401
from ray_tpu.rllib.algorithms.dqn.dqn import DQN, DQNConfig  # noqa: F401
from ray_tpu.rllib.algorithms.impala.impala import IMPALA, IMPALAConfig  # noqa: F401
from ray_tpu.rllib.algorithms.marwil.marwil import MARWIL, MARWILConfig  # noqa: F401
from ray_tpu.rllib.algorithms.ppo.ppo import PPO, PPOConfig  # noqa: F401
from ray_tpu.rllib.algorithms.sac.sac import SAC, SACConfig  # noqa: F401
from ray_tpu.rllib.connectors import ConnectorPipeline, ConnectorV2  # noqa: F401
from ray_tpu.rllib.core.rl_module import MLPModule, RLModule, RLModuleSpec  # noqa: F401
from ray_tpu.rllib.env.multi_agent import MultiAgentEnv, MultiAgentEnvRunner  # noqa: F401
from ray_tpu.rllib.utils.replay_buffers import (  # noqa: F401
    EpisodeReplayBuffer,
    PrioritizedEpisodeReplayBuffer,
)
