"""Env runners: CPU rollout workers (reference:
rllib/env/single_agent_env_runner.py:68, sample() :147 and
rllib/env/env_runner_group.py:70).

TPU-native split: rollouts stay on CPU (gymnasium vector envs + a jitted
CPU forward of the same functional RLModule the TPU learner trains);
weight sync ships a params pytree — there is no separate inference model
class to keep in lockstep.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

import ray_tpu
from ray_tpu.rllib.env.episode import Episode


def _make_env(env_id, env_config, num_envs):
    import gymnasium as gym

    return gym.make_vec(env_id, num_envs=num_envs, vectorization_mode="sync", **(env_config or {}))


class SingleAgentEnvRunner:
    """Steps `num_envs` vectorized envs; actions from the module's
    exploration pass. Runs inline (local mode) or as a remote actor."""

    def __init__(self, module_spec, env_id: str, env_config: dict | None = None, num_envs: int = 1, seed: int = 0, worker_idx: int = 0, env_to_module=None, module_to_env=None):
        self.envs = _make_env(env_id, env_config, num_envs)
        self.num_envs = num_envs
        self.module = module_spec.build()
        # connector pipelines (rllib/connectors/connector.py, reference
        # connector_v2.py): obs transform applied ONCE at receipt so the
        # module forward AND the learner (via stored episode obs) see the
        # same representation; action transform applied only on the way
        # into env.step (episodes keep module-space actions so replayed
        # logp/Q inputs stay consistent)
        self._env_to_module = env_to_module
        self._module_to_env = module_to_env
        self.params = None
        # rollouts are latency-bound host loops: pin them to the CPU
        # backend when one is registered, even if the process default is a
        # (possibly remote/tunneled) TPU — per-step eager ops on a remote
        # device would make each env step a network round trip
        try:
            self._device = jax.local_devices(backend="cpu")[0]
        except Exception:
            self._device = None
        self._key = self._put(jax.random.PRNGKey(seed + 10_000 * worker_idx))
        self._fwd = jax.jit(self.module.forward_exploration)
        obs, _ = self.envs.reset(seed=seed + 10_000 * worker_idx)
        obs = self._obs_transform(obs)
        self._obs = obs
        self._building = [Episode() for _ in range(num_envs)]
        for ep, o in zip(self._building, obs):
            ep.obs.append(np.asarray(o))
        # gymnasium >=1.0 NEXT_STEP autoreset: the step after a terminal
        # ignores the action and returns the reset obs — not a transition
        self._pending_reset = np.zeros(num_envs, dtype=bool)
        # true per-env episode return, accumulated across segment cuts
        self._return_acc = np.zeros(num_envs, dtype=np.float64)
        from collections import deque

        self._episode_returns: deque = deque(maxlen=100)
        self._episodes_this_sample = 0

    def _obs_transform(self, obs):
        if self._env_to_module is None:
            return obs
        return self._env_to_module(obs, action_space=self.envs.single_action_space)

    def _action_transform(self, actions):
        if self._module_to_env is None:
            return actions
        return self._module_to_env(actions, action_space=self.envs.single_action_space)

    def get_connector_states(self) -> dict:
        return {
            "env_to_module": self._env_to_module.get_state() if self._env_to_module else {},
            "module_to_env": self._module_to_env.get_state() if self._module_to_env else {},
        }

    def _put(self, x):
        return jax.device_put(x, self._device) if self._device is not None else jnp.asarray(x)

    def _on_device(self):
        import contextlib

        return jax.default_device(self._device) if self._device is not None else contextlib.nullcontext()

    def set_weights(self, params):
        self.params = jax.tree.map(self._put, params)

    def set_exploration(self, **kw):
        """Push exploration knobs (e.g. an annealed epsilon) onto the
        module's action-distribution class (reference: exploration config
        updates pushed to workers)."""
        cls = self.module.action_dist_cls
        for k, v in kw.items():
            if hasattr(cls, k):
                setattr(cls, k, v)

    def get_spaces(self):
        return self.envs.single_observation_space, self.envs.single_action_space

    def sample(self, num_steps: int, explore: bool = True) -> tuple[list[dict], dict]:
        """Collect ~num_steps env steps (across vector envs); returns
        (episode segment batches, metrics). Segments end at terminal,
        truncation, or collection cut; each carries a bootstrap obs row."""
        with self._on_device():
            return self._sample(num_steps, explore)

    def _sample(self, num_steps: int, explore: bool = True) -> tuple[list[dict], dict]:
        assert self.params is not None, "set_weights before sample"
        segments: list[Episode] = []
        steps_left = num_steps
        self._episodes_this_sample = 0
        dist = self.module.action_dist_cls
        while steps_left > 0:
            out = self._fwd(self.params, jnp.asarray(self._obs))
            inputs = out["action_dist_inputs"]
            if explore:
                self._key, k = jax.random.split(self._key)
                actions = dist.sample(k, inputs)
            else:
                actions = dist.deterministic(inputs)
            logp = dist.logp(inputs, actions)
            actions_np = np.asarray(actions)
            logp_np = np.asarray(logp)
            vf_np = np.asarray(out["vf"])
            obs, rewards, terms, truncs, _ = self.envs.step(self._action_transform(actions_np))
            obs = self._obs_transform(obs)
            for i in range(self.num_envs):
                if self._pending_reset[i]:
                    # this step reset env i: obs[i] is the new episode's
                    # initial obs, the action was ignored — record nothing
                    fresh = Episode()
                    fresh.obs.append(np.asarray(obs[i]))
                    self._building[i] = fresh
                    self._pending_reset[i] = False
                    continue
                ep = self._building[i]
                ep.actions.append(actions_np[i])
                ep.rewards.append(float(rewards[i]))
                ep.logp.append(float(logp_np[i]))
                ep.vf_preds.append(float(vf_np[i]))
                ep.obs.append(np.asarray(obs[i]))  # NEXT_STEP mode: true final obs at a terminal
                self._return_acc[i] += float(rewards[i])
                if terms[i] or truncs[i]:
                    ep.is_terminated = bool(terms[i])
                    self._episode_returns.append(float(self._return_acc[i]))
                    self._episodes_this_sample += 1
                    self._return_acc[i] = 0.0
                    segments.append(ep)
                    self._pending_reset[i] = True
            self._obs = obs
            steps_left -= self.num_envs
        # cut still-running episodes into segments (bootstrap from last obs)
        for i in range(self.num_envs):
            if self._pending_reset[i]:
                continue  # episode already emitted; env resets next step
            ep = self._building[i]
            if len(ep) > 0:
                segments.append(ep)
                fresh = Episode()
                fresh.obs.append(ep.obs[-1])
                self._building[i] = fresh
        returns = list(self._episode_returns)
        metrics = {
            "episode_return_mean": float(np.mean(returns)) if returns else float("nan"),
            "num_episodes": self._episodes_this_sample,
            "num_env_steps": int(num_steps - steps_left),
        }
        return [s.to_batch() for s in segments], metrics


@ray_tpu.remote
class _EnvRunnerActor(SingleAgentEnvRunner):
    pass


class EnvRunnerGroup:
    """N remote env-runner actors, or one local runner when
    num_env_runners == 0 (reference env_runner_group.py local-worker
    semantics)."""

    def __init__(self, module_spec, env_id, env_config=None, num_env_runners: int = 0, num_envs_per_env_runner: int = 1, seed: int = 0, output: str | None = None, env_to_module=None, module_to_env=None):
        self.num_env_runners = num_env_runners
        # offline-data recording (reference: offline/json_writer.py via
        # config.offline_data(output=...)): every collected episode batch
        # is appended to JSONL shards as it arrives at the driver
        self._writer = None
        if output:
            from ray_tpu.rllib.offline import JsonWriter

            self._writer = JsonWriter(output)
        if num_env_runners == 0:
            self._local = SingleAgentEnvRunner(
                module_spec, env_id, env_config, num_envs_per_env_runner, seed,
                env_to_module=env_to_module, module_to_env=module_to_env,
            )
            self._actors = []
        else:
            self._local = None
            self._actors = [
                _EnvRunnerActor.remote(
                    module_spec, env_id, env_config, num_envs_per_env_runner, seed, worker_idx=i + 1,
                    env_to_module=env_to_module, module_to_env=module_to_env,
                )
                for i in range(num_env_runners)
            ]

    def get_spaces(self):
        if self._local is not None:
            return self._local.get_spaces()
        return ray_tpu.get(self._actors[0].get_spaces.remote())

    def sync_weights(self, params):
        params = jax.tree.map(np.asarray, params)
        if self._local is not None:
            self._local.set_weights(params)
        else:
            ray_tpu.get([a.set_weights.remote(params) for a in self._actors])

    def set_exploration(self, **kw):
        if self._local is not None:
            self._local.set_exploration(**kw)
        else:
            ray_tpu.get([a.set_exploration.remote(**kw) for a in self._actors])

    def sample(self, num_steps: int, explore: bool = True):
        """Returns (all segment batches, per-runner metrics list)."""
        if self._local is not None:
            segs, m = self._local.sample(num_steps, explore)
            self._record(segs)
            return segs, [m]
        return self.collect(self.sample_async(num_steps, explore))

    def sample_async(self, num_steps: int, explore: bool = True):
        """Kick off sampling on every remote runner; returns refs for
        collect() (lets IMPALA overlap sampling with the learner update)."""
        assert self._actors, "sample_async requires remote env runners"
        per = max(1, num_steps // len(self._actors))
        return [a.sample.remote(per, explore) for a in self._actors]

    def collect(self, refs):
        outs = ray_tpu.get(refs)
        segments: list[dict] = []
        metrics = []
        for segs, m in outs:
            segments.extend(segs)
            metrics.append(m)
        self._record(segments)
        return segments, metrics

    def _record(self, segments):
        if self._writer is not None:
            for s in segments:
                self._writer.write(s)

    def stop(self):
        if self._writer is not None:
            self._writer.close()
            self._writer = None
        for a in self._actors:
            ray_tpu.kill(a)
        self._actors = []
