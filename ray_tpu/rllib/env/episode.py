"""Episode container (reference: rllib/env/single_agent_episode.py, pared
to the fields the JAX learners consume). Stores numpy arrays; converted to
device arrays only inside the learner's jitted update."""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


@dataclass
class Episode:
    obs: list = field(default_factory=list)  # len T+1 (includes final obs)
    actions: list = field(default_factory=list)  # len T
    rewards: list = field(default_factory=list)
    logp: list = field(default_factory=list)  # behavior log-probs
    vf_preds: list = field(default_factory=list)
    is_terminated: bool = False  # env terminal (vs truncated/cut)

    def __len__(self) -> int:
        return len(self.actions)

    @property
    def total_reward(self) -> float:
        return float(sum(self.rewards))

    def to_batch(self) -> dict:
        """Stacked numpy views: obs has T+1 rows (last = bootstrap obs)."""
        return {
            "obs": np.asarray(self.obs, dtype=np.float32),
            "actions": np.asarray(self.actions),
            "rewards": np.asarray(self.rewards, dtype=np.float32),
            "logp": np.asarray(self.logp, dtype=np.float32),
            "vf_preds": np.asarray(self.vf_preds, dtype=np.float32),
            "terminated": np.asarray(self.is_terminated),
        }
