from ray_tpu.rllib.env.env_runner import EnvRunnerGroup, SingleAgentEnvRunner  # noqa: F401
from ray_tpu.rllib.env.episode import Episode  # noqa: F401
