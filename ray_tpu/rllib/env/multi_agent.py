"""Multi-agent environments + per-policy rollout collection.

Reference parity: rllib/env/multi_agent_env.py (the dict-keyed env
protocol with the "__all__" done flag) and
rllib/env/multi_agent_env_runner.py (one runner stepping all agents,
routing each agent's experience to its policy's module via
policy_mapping_fn). Per-policy batches feed independent jitted learners —
the TPU-native analogue of the reference's MultiRLModule update.
"""

from __future__ import annotations

from collections import defaultdict

import jax
import jax.numpy as jnp
import numpy as np

import ray_tpu
from ray_tpu.rllib.env.episode import Episode


class MultiAgentEnv:
    """Dict-keyed env protocol (reference: multi_agent_env.py):

    reset() -> (obs_dict, info_dict)
    step(action_dict) -> (obs, rewards, terminateds, truncateds, infos),
    each keyed by agent id; terminateds/truncateds carry "__all__".
    Agents may appear/disappear between steps (only act for present ids).
    """

    possible_agents: list = []
    observation_spaces: dict = {}
    action_spaces: dict = {}

    def reset(self, *, seed=None, options=None):
        raise NotImplementedError

    def step(self, action_dict: dict):
        raise NotImplementedError


class MultiAgentEnvRunner:
    """Steps one multi-agent env; each agent's actions come from the
    module of the policy its id maps to; experience is routed back into
    per-policy episode segments (reference: multi_agent_env_runner.py
    sample())."""

    def __init__(self, env_factory, module_specs: dict, policy_mapping_fn=None, seed: int = 0, worker_idx: int = 0):
        self.env = env_factory() if callable(env_factory) else env_factory
        self.modules = {pid: spec.build() for pid, spec in module_specs.items()}
        self.policy_mapping_fn = policy_mapping_fn or (lambda agent_id: agent_id)
        self.params: dict = {}
        try:
            self._device = jax.local_devices(backend="cpu")[0]
        except Exception:
            self._device = None
        self._key = self._put(jax.random.PRNGKey(seed + 10_000 * worker_idx))
        self._fwd = {pid: jax.jit(m.forward_exploration) for pid, m in self.modules.items()}
        self._seed = seed + 10_000 * worker_idx
        self._obs, _ = self.env.reset(seed=self._seed)
        self._building: dict = {}  # agent_id -> Episode
        self._done_agents: set = set()  # terminated before __all__: no more actions
        self._episode_returns: list = []
        for aid, o in self._obs.items():
            ep = Episode()
            ep.obs.append(np.asarray(o))
            self._building[aid] = ep

    def _put(self, x):
        return jax.device_put(x, self._device) if self._device is not None else jnp.asarray(x)

    def _on_device(self):
        import contextlib

        return jax.default_device(self._device) if self._device is not None else contextlib.nullcontext()

    def set_weights(self, params_by_policy: dict):
        self.params = {pid: jax.tree.map(self._put, p) for pid, p in params_by_policy.items()}

    def sample(self, num_steps: int, explore: bool = True) -> tuple[dict, dict]:
        """Collect ~num_steps env steps. Returns
        ({policy_id: [episode batches]}, metrics). Rollout math is pinned
        to the CPU device (a remote-TPU default would turn each env step
        into a network round trip)."""
        with self._on_device():
            return self._sample(num_steps, explore)

    def _sample(self, num_steps: int, explore: bool = True) -> tuple[dict, dict]:
        assert self.params, "set_weights before sample"
        out_segments: dict[str, list] = defaultdict(list)
        episodes_done = 0
        returns: list[float] = []
        for _ in range(num_steps):
            # group LIVE agents by policy for batched forwards (an agent
            # terminated before __all__ takes no further actions)
            by_policy: dict[str, list] = defaultdict(list)
            for aid in self._obs:
                if aid not in self._done_agents:
                    by_policy[self.policy_mapping_fn(aid)].append(aid)
            if not by_policy:
                # everyone done but env never raised __all__: reset
                self._seed += 1
                self._obs, _ = self.env.reset(seed=self._seed)
                self._done_agents.clear()
                self._building = {}
                for aid, o in self._obs.items():
                    ep = Episode()
                    ep.obs.append(np.asarray(o))
                    self._building[aid] = ep
                continue
            actions: dict = {}
            step_info: dict = {}
            for pid, aids in by_policy.items():
                obs_arr = jnp.asarray(np.stack([np.asarray(self._obs[a], np.float32) for a in aids]))
                fwd = self._fwd[pid](self.params[pid], obs_arr)
                dist = self.modules[pid].action_dist_cls
                inputs = fwd["action_dist_inputs"]
                if explore:
                    self._key, k = jax.random.split(self._key)
                    acts = dist.sample(k, inputs)
                else:
                    acts = dist.deterministic(inputs)
                logp = np.asarray(dist.logp(inputs, acts))
                vf = np.asarray(fwd["vf"])
                acts = np.asarray(acts)
                for i, a in enumerate(aids):
                    actions[a] = acts[i]
                    step_info[a] = (float(logp[i]), float(vf[i]))
            obs, rewards, terms, truncs, _ = self.env.step(actions)
            done_all = bool(terms.get("__all__", False) or truncs.get("__all__", False))
            for aid, act in actions.items():
                ep = self._building.get(aid)
                if ep is None:
                    continue
                lp, v = step_info[aid]
                ep.actions.append(act)
                ep.rewards.append(float(rewards.get(aid, 0.0)))
                ep.logp.append(lp)
                ep.vf_preds.append(v)
                nxt = obs.get(aid, ep.obs[-1])
                ep.obs.append(np.asarray(nxt))
                if terms.get(aid, False) or truncs.get(aid, False) or done_all:
                    ep.is_terminated = bool(terms.get(aid, False) or terms.get("__all__", False))
                    out_segments[self.policy_mapping_fn(aid)].append(ep)
                    returns.append(ep.total_reward)
                    self._building.pop(aid, None)
                    if not done_all:
                        self._done_agents.add(aid)  # dead until the episode ends
            if done_all:
                episodes_done += 1
                self._seed += 1
                self._obs, _ = self.env.reset(seed=self._seed)
                self._building = {}
                self._done_agents.clear()
            else:
                self._obs = obs
            for aid, o in self._obs.items():
                if aid not in self._building and aid not in self._done_agents:
                    ep = Episode()
                    ep.obs.append(np.asarray(o))
                    self._building[aid] = ep
        # cut still-running agent episodes (bootstrap from last obs)
        for aid, ep in list(self._building.items()):
            if len(ep) > 0:
                out_segments[self.policy_mapping_fn(aid)].append(ep)
                fresh = Episode()
                fresh.obs.append(ep.obs[-1])
                self._building[aid] = fresh
        metrics = {
            "episode_return_mean": float(np.mean(returns)) if returns else float("nan"),
            "num_episodes": episodes_done,
        }
        return {pid: [s.to_batch() for s in segs] for pid, segs in out_segments.items()}, metrics


@ray_tpu.remote
class MultiAgentEnvRunnerActor(MultiAgentEnvRunner):
    pass
