"""Replay buffers for off-policy RL.

Reference parity: rllib/utils/replay_buffers/episode_replay_buffer.py
(uniform transition sampling out of stored episodes) and
prioritized_episode_replay_buffer.py (proportional prioritization over a
segment/sum tree). TPU-native shape: transitions live in preallocated
numpy ring arrays so `sample()` returns contiguous stacked batches the
jitted TD-loss consumes without per-row Python work.
"""

from __future__ import annotations

import numpy as np


class SumTree:
    """Binary sum tree over `capacity` priorities: O(log n) update and
    prefix-sum sampling (reference: rllib/execution/segment_tree.py)."""

    def __init__(self, capacity: int):
        self.capacity = int(capacity)
        size = 1
        while size < self.capacity:
            size *= 2
        self._size = size
        self._tree = np.zeros(2 * size, dtype=np.float64)

    def set(self, idx: int, value: float):
        i = idx + self._size
        self._tree[i] = value
        i //= 2
        while i >= 1:
            self._tree[i] = self._tree[2 * i] + self._tree[2 * i + 1]
            i //= 2

    def get(self, idx: int) -> float:
        return float(self._tree[idx + self._size])

    def total(self) -> float:
        return float(self._tree[1])

    def prefix_index(self, mass: float) -> int:
        """Largest idx with prefix_sum(idx) <= mass (proportional pick)."""
        i = 1
        while i < self._size:
            left = self._tree[2 * i]
            if mass < left:
                i = 2 * i
            else:
                mass -= left
                i = 2 * i + 1
        return min(i - self._size, self.capacity - 1)


class EpisodeReplayBuffer:
    """Uniform transition replay. `add(episode_batch)` ingests one episode
    segment (the env runner's to_batch dict: obs has T+1 rows); `sample(n)`
    returns {obs, actions, rewards, next_obs, done} stacked arrays."""

    def __init__(self, capacity: int = 100_000, seed: int = 0):
        self.capacity = int(capacity)
        self._arrays: dict[str, np.ndarray] | None = None
        self._write = 0
        self._count = 0
        self._rng = np.random.default_rng(seed)

    def _ensure(self, obs, action):
        if self._arrays is not None:
            return
        obs_shape = np.asarray(obs).shape
        act = np.asarray(action)
        self._arrays = {
            "obs": np.zeros((self.capacity, *obs_shape), np.float32),
            "next_obs": np.zeros((self.capacity, *obs_shape), np.float32),
            "actions": np.zeros((self.capacity, *act.shape), act.dtype if act.dtype != np.float64 else np.float32),
            "rewards": np.zeros((self.capacity,), np.float32),
            "done": np.zeros((self.capacity,), np.float32),
        }

    def __len__(self) -> int:
        return self._count

    def _add_row(self, obs, next_obs, action, reward, done) -> int:
        i = self._write
        a = self._arrays
        a["obs"][i] = obs
        a["next_obs"][i] = next_obs
        a["actions"][i] = action
        a["rewards"][i] = reward
        a["done"][i] = done
        self._write = (self._write + 1) % self.capacity
        self._count = min(self._count + 1, self.capacity)
        return i

    def add(self, episode_batch: dict) -> list[int]:
        """Ingest an episode segment; returns the row indices written.
        `done` marks true terminals only — truncation/segment cuts
        bootstrap (reference: episode_replay_buffer add() semantics)."""
        obs = np.asarray(episode_batch["obs"], np.float32)
        actions = np.asarray(episode_batch["actions"])
        rewards = np.asarray(episode_batch["rewards"], np.float32)
        terminated = bool(episode_batch.get("terminated", False))
        T = len(actions)
        if T == 0:
            return []
        self._ensure(obs[0], actions[0])
        rows = []
        for t in range(T):
            done = terminated and t == T - 1
            rows.append(self._add_row(obs[t], obs[t + 1], actions[t], rewards[t], float(done)))
        return rows

    def sample(self, n: int) -> dict:
        idx = self._rng.integers(0, self._count, size=n)
        return self._gather(idx)

    def _gather(self, idx) -> dict:
        a = self._arrays
        return {k: v[idx] for k, v in a.items()}


class PrioritizedEpisodeReplayBuffer(EpisodeReplayBuffer):
    """Proportional prioritized replay (reference:
    prioritized_episode_replay_buffer.py): P(i) ~ priority_i^alpha, with
    importance weights (N * P(i))^-beta normalized by the max weight.
    New transitions enter at max priority; update_priorities() feeds
    |td_error| back after each learner step."""

    def __init__(self, capacity: int = 100_000, alpha: float = 0.6, beta: float = 0.4, eps: float = 1e-6, seed: int = 0):
        super().__init__(capacity, seed=seed)
        self.alpha = float(alpha)
        self.beta = float(beta)
        self.eps = float(eps)
        self._tree = SumTree(self.capacity)
        self._max_priority = 1.0

    def _add_row(self, *args) -> int:
        i = super()._add_row(*args)
        self._tree.set(i, self._max_priority**self.alpha)
        return i

    def sample(self, n: int) -> dict:
        total = self._tree.total()
        masses = (self._rng.random(n) + np.arange(n)) / n * total  # stratified
        idx = np.array([self._tree.prefix_index(m) for m in masses], dtype=np.int64)
        idx = np.minimum(idx, self._count - 1)
        batch = self._gather(idx)
        probs = np.array([self._tree.get(i) for i in idx]) / max(total, 1e-12)
        weights = (self._count * np.maximum(probs, 1e-12)) ** (-self.beta)
        batch["weights"] = (weights / weights.max()).astype(np.float32)
        batch["batch_indices"] = idx
        return batch

    def update_priorities(self, idx, td_errors):
        for i, td in zip(np.asarray(idx), np.asarray(td_errors)):
            p = float(abs(td)) + self.eps
            self._max_priority = max(self._max_priority, p)
            self._tree.set(int(i), p**self.alpha)
