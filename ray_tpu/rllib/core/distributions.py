"""Action distributions as pure functions over distribution inputs.

Reference parity: rllib/models/distributions.py + torch distribution
wrappers (rllib/models/torch/torch_distributions.py). Here a distribution
is a namespace of pure jnp functions keyed on the module's output tensor
("logits" / mean+logstd), so they compose with jit/grad with no objects on
the trace.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class Categorical:
    """Discrete actions from unnormalized logits [..., n_actions]."""

    @staticmethod
    def sample(key, logits):
        return jax.random.categorical(key, logits, axis=-1)

    @staticmethod
    def logp(logits, actions):
        logp_all = jax.nn.log_softmax(logits, axis=-1)
        return jnp.take_along_axis(logp_all, actions[..., None].astype(jnp.int32), axis=-1)[..., 0]

    @staticmethod
    def entropy(logits):
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.sum(jnp.exp(logp) * logp, axis=-1)

    @staticmethod
    def kl(logits_p, logits_q):
        logp = jax.nn.log_softmax(logits_p, axis=-1)
        logq = jax.nn.log_softmax(logits_q, axis=-1)
        return jnp.sum(jnp.exp(logp) * (logp - logq), axis=-1)

    @staticmethod
    def deterministic(logits):
        return jnp.argmax(logits, axis=-1)


class DiagGaussian:
    """Continuous actions; inputs [..., 2*dim] = concat(mean, log_std)."""

    @staticmethod
    def _split(inputs):
        mean, log_std = jnp.split(inputs, 2, axis=-1)
        return mean, jnp.clip(log_std, -20.0, 2.0)

    @staticmethod
    def sample(key, inputs):
        mean, log_std = DiagGaussian._split(inputs)
        return mean + jnp.exp(log_std) * jax.random.normal(key, mean.shape)

    @staticmethod
    def logp(inputs, actions):
        mean, log_std = DiagGaussian._split(inputs)
        var = jnp.exp(2 * log_std)
        return jnp.sum(-0.5 * ((actions - mean) ** 2 / var + 2 * log_std + jnp.log(2 * jnp.pi)), axis=-1)

    @staticmethod
    def entropy(inputs):
        _, log_std = DiagGaussian._split(inputs)
        return jnp.sum(log_std + 0.5 * jnp.log(2 * jnp.pi * jnp.e), axis=-1)

    @staticmethod
    def kl(inputs_p, inputs_q):
        mp, lp = DiagGaussian._split(inputs_p)
        mq, lq = DiagGaussian._split(inputs_q)
        return jnp.sum(lq - lp + (jnp.exp(2 * lp) + (mp - mq) ** 2) / (2 * jnp.exp(2 * lq)) - 0.5, axis=-1)

    @staticmethod
    def deterministic(inputs):
        mean, _ = DiagGaussian._split(inputs)
        return mean


def make_squashed_gaussian(low, high):
    """Tanh-squashed diagonal gaussian scaled to [low, high] — the SAC
    policy distribution (reference: TorchSquashedGaussian in
    rllib/models/torch/torch_distributions.py). Built per action space
    like DQN's epsilon-greedy factory: the bounds are baked into the
    class so env runners use it through the generic dist interface."""
    import numpy as np

    low_a = jnp.asarray(np.asarray(low, dtype=np.float32))
    high_a = jnp.asarray(np.asarray(high, dtype=np.float32))
    scale = (high_a - low_a) * 0.5
    mid = (high_a + low_a) * 0.5

    class SquashedGaussian:
        low = low_a
        high = high_a

        @staticmethod
        def _squash(u):
            return mid + scale * jnp.tanh(u)

        @staticmethod
        def sample(key, inputs):
            mean, log_std = DiagGaussian._split(inputs)
            u = mean + jnp.exp(log_std) * jax.random.normal(key, mean.shape)
            return SquashedGaussian._squash(u)

        @staticmethod
        def logp(inputs, actions):
            # invert the squash; clip keeps atanh finite at the bounds
            t = jnp.clip((actions - mid) / scale, -0.999999, 0.999999)
            u = jnp.arctanh(t)
            base = DiagGaussian.logp(inputs, u)
            # |d a / d u| = scale * (1 - tanh(u)^2)
            correction = jnp.sum(jnp.log(scale * (1.0 - t**2) + 1e-9), axis=-1)
            return base - correction

        @staticmethod
        def deterministic(inputs):
            mean, _ = DiagGaussian._split(inputs)
            return SquashedGaussian._squash(mean)

        @staticmethod
        def entropy(inputs):
            # gaussian entropy upper bound (exact squashed entropy has no
            # closed form; used only for metrics)
            return DiagGaussian.entropy(inputs)

    return SquashedGaussian
