"""Learner / LearnerGroup (reference: rllib/core/learner/learner.py:112,
learner_group.py:101,256).

TPU-native shape: a Learner owns a functional RLModule's params + optax
state and a *jitted* minibatch step; `compute_losses` is the per-algorithm
override point (reference learner.py:929). Multi-learner data parallelism
replaces torch DDP with an explicit grads-allreduce through
ray_tpu.collective between the jitted grad and apply steps (the host/DCN
path; single-process multi-device learners instead jit over a mesh).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax

import ray_tpu
from ray_tpu import collective


class Learner:
    def __init__(self, module_spec, config):
        self.config = config
        self.module = module_spec.build()
        self.params = None
        self.opt_state = None
        self._step = None
        self._metrics: dict = {}

    # -- construction --
    def build(self, seed: int = 0):
        self.params = self.module.init(jax.random.PRNGKey(seed))
        self.optimizer = self._make_optimizer()
        self.opt_state = self.optimizer.init(self.params)
        self._grad_fn = jax.jit(jax.grad(self._loss_for_grad, has_aux=True))
        self._apply_fn = jax.jit(self._apply)

    def _make_optimizer(self):
        clip = getattr(self.config, "grad_clip", None)
        tx = optax.adam(self.config.lr)
        if clip:
            tx = optax.chain(optax.clip_by_global_norm(clip), tx)
        return tx

    def _loss_for_grad(self, params, batch):
        loss, aux = self.compute_losses(params, batch)
        return loss, aux

    def _apply(self, params, opt_state, grads):
        updates, opt_state = self.optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    # -- per-algorithm override --
    def compute_losses(self, params, batch) -> tuple[jax.Array, dict]:
        raise NotImplementedError

    # -- gradient sync seam (overridden in multi-learner actors) --
    def _sync_grads(self, grads):
        return grads

    # -- update loop --
    def update(self, batch: dict, minibatch_size: int | None = None, num_epochs: int = 1, shuffle: bool = True, seed: int = 0) -> dict:
        """Minibatch-SGD over `batch` (row-major dict of arrays);
        returns averaged loss metrics."""
        n = len(batch["obs"])
        minibatch_size = minibatch_size or n
        rng = np.random.default_rng(seed)
        metrics_acc: dict[str, list] = {}
        for _ in range(num_epochs):
            idx = rng.permutation(n) if shuffle else np.arange(n)
            for start in range(0, n, minibatch_size):
                rows = idx[start : start + minibatch_size]
                if len(rows) < max(2, minibatch_size // 2) and start > 0:
                    continue  # drop tiny trailing minibatch
                mb = {k: jnp.asarray(v[rows]) for k, v in batch.items() if hasattr(v, "__getitem__")}
                grads, aux = self._grad_fn(self.params, mb)
                grads = self._sync_grads(grads)
                self.params, self.opt_state = self._apply_fn(self.params, self.opt_state, grads)
                for k, v in aux.items():
                    metrics_acc.setdefault(k, []).append(float(v))
        self._metrics = {k: float(np.mean(v)) for k, v in metrics_acc.items()}
        return self._metrics

    # -- state / weights --
    def get_weights(self):
        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, params):
        self.params = jax.tree.map(jnp.asarray, params)

    def get_state(self) -> dict:
        return {
            "params": jax.tree.map(np.asarray, self.params),
            "opt_state": jax.tree.map(lambda x: np.asarray(x) if hasattr(x, "shape") else x, self.opt_state),
        }

    def set_state(self, state: dict):
        self.params = jax.tree.map(jnp.asarray, state["params"])
        self.opt_state = jax.tree.map(
            lambda cur, new: jnp.asarray(new) if hasattr(cur, "shape") else new, self.opt_state, state["opt_state"]
        )


class _LearnerActorMixin:
    """Gradient allreduce over the learner collective group."""

    def setup_collective(self, world_size: int, rank: int, group_name: str):
        self._group_name = group_name
        collective.init_collective_group(world_size, rank, group_name=group_name)

    def _sync_grads(self, grads):
        if getattr(self, "_group_name", None) is None:
            return grads
        flat, treedef = jax.tree.flatten(grads)
        sizes = [int(np.prod(x.shape)) for x in flat]
        buf = np.concatenate([np.asarray(x, dtype=np.float32).ravel() for x in flat])
        out = collective.allreduce(buf, group_name=self._group_name)
        out = out / collective.get_world_size(self._group_name)
        parts = np.split(out, np.cumsum(sizes)[:-1])
        return jax.tree.unflatten(treedef, [jnp.asarray(p.reshape(x.shape)) for p, x in zip(parts, flat)])


class LearnerGroup:
    """0 remote learners -> one in-process Learner; N >= 1 -> N learner
    actors, per-update batch rows sharded across them, grads allreduced
    (reference learner_group.py:256 update)."""

    def __init__(self, learner_cls, module_spec, config, num_learners: int = 0):
        self.num_learners = num_learners
        if num_learners == 0:
            self._local = learner_cls(module_spec, config)
            self._local.build(seed=config.seed)
            self._actors = []
        else:
            self._local = None
            actor_cls = ray_tpu.remote(type(f"_{learner_cls.__name__}Actor", (_LearnerActorMixin, learner_cls), {}))
            self._actors = [actor_cls.remote(module_spec, config) for _ in range(num_learners)]
            ray_tpu.get([a.build.remote(seed=config.seed) for a in self._actors])
            group = f"rllib_learners_{id(self)}"
            ray_tpu.get([a.setup_collective.remote(num_learners, i, group) for i, a in enumerate(self._actors)])
            # identical init on every learner (same seed) = synced start

    def update(self, batch: dict, **kw) -> list[dict]:
        if self._local is not None:
            return [self._local.update(batch, **kw)]
        n = len(batch["obs"])
        k = len(self._actors)
        if n < k:
            raise ValueError(f"batch of {n} rows cannot be sharded across {k} learners")
        # every learner MUST take an identical-size shard: the per-minibatch
        # grad allreduce is a blocking collective, so unequal shard sizes
        # (hence unequal step counts) would deadlock the group. Rather than
        # dropping the n % k remainder, pad with wrap-around rows so every
        # collected row reaches some learner (a few duplicates, no drops).
        shard = -(-n // k)  # ceil
        if shard * k > n:
            pad = np.arange(shard * k - n) % n
            batch = {k2: np.concatenate([v, v[pad]], axis=0) for k2, v in batch.items()}
        refs = []
        for i, a in enumerate(self._actors):
            rows = slice(i * shard, (i + 1) * shard)
            sub = {k2: v[rows] for k2, v in batch.items()}
            refs.append(a.update.remote(sub, **kw))
        return ray_tpu.get(refs)

    def get_weights(self):
        if self._local is not None:
            return self._local.get_weights()
        return ray_tpu.get(self._actors[0].get_weights.remote())

    def get_state(self) -> dict:
        if self._local is not None:
            return self._local.get_state()
        return ray_tpu.get(self._actors[0].get_state.remote())

    def set_state(self, state: dict):
        if self._local is not None:
            self._local.set_state(state)
        else:
            ray_tpu.get([a.set_state.remote(state) for a in self._actors])

    def stop(self):
        for a in self._actors:
            ray_tpu.kill(a)
        self._actors = []
