"""RLModule: the model abstraction (reference: rllib/core/rl_module/rl_module.py).

TPU-native shape: a module is *stateless* — `init` returns a param pytree
and `forward_*` are pure functions of (params, batch), so the same module
object can be jitted on a learner mesh, vmapped in an env runner, and
serialized by spec (class + config) without touching torch Modules.

forward_inference / forward_exploration / forward_train mirror the
reference's three passes (rl_module.py forward_inference/_exploration/_train).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.core.distributions import Categorical, DiagGaussian


def _space_size(space) -> int:
    if hasattr(space, "n"):  # Discrete
        return int(space.n)
    return int(np.prod(space.shape))


@dataclass
class RLModuleSpec:
    """Serializable recipe for constructing a module on any worker
    (reference: rllib/core/rl_module/rl_module.py RLModuleSpec)."""

    module_class: type | None = None
    observation_space: Any = None
    action_space: Any = None
    model_config: dict = field(default_factory=dict)

    def build(self) -> "RLModule":
        cls = self.module_class or MLPModule
        return cls(self.observation_space, self.action_space, self.model_config)


class RLModule:
    """Base: subclasses define init(key) -> params and forward(params, obs)
    -> {"action_dist_inputs", "vf"}; distribution cls picked from the
    action space."""

    def __init__(self, observation_space, action_space, model_config: dict | None = None):
        self.observation_space = observation_space
        self.action_space = action_space
        self.model_config = dict(model_config or {})
        self.action_dist_cls = Categorical if hasattr(action_space, "n") else DiagGaussian

    # -- to implement --
    def init(self, key) -> Any:
        raise NotImplementedError

    def forward(self, params, obs) -> dict:
        raise NotImplementedError

    # -- shared passes (reference rl_module.py forward_* split) --
    def forward_inference(self, params, obs) -> dict:
        return self.forward(params, obs)

    def forward_exploration(self, params, obs) -> dict:
        return self.forward(params, obs)

    def forward_train(self, params, batch) -> dict:
        return self.forward(params, batch["obs"])

    def spec(self) -> RLModuleSpec:
        return RLModuleSpec(type(self), self.observation_space, self.action_space, self.model_config)


class MLPModule(RLModule):
    """Separate policy and value MLP towers with tanh activations — the
    default fcnet of the reference (rllib catalog fcnet_hiddens=[256,256])
    as a functional pytree."""

    def __init__(self, observation_space, action_space, model_config=None):
        super().__init__(observation_space, action_space, model_config)
        self.hiddens = tuple(self.model_config.get("fcnet_hiddens", (256, 256)))
        self.obs_dim = _space_size(observation_space)
        if hasattr(action_space, "n"):
            self.out_dim = int(action_space.n)
        else:
            self.out_dim = 2 * int(np.prod(action_space.shape))

    def _mlp_init(self, key, sizes, final_scale=0.01):
        params = []
        for i, (fan_in, fan_out) in enumerate(zip(sizes[:-1], sizes[1:])):
            key, k = jax.random.split(key)
            scale = final_scale if i == len(sizes) - 2 else 1.0
            w = jax.random.orthogonal(k, max(fan_in, fan_out))[:fan_in, :fan_out] * scale
            params.append({"w": w.astype(jnp.float32), "b": jnp.zeros((fan_out,), jnp.float32)})
        return params

    def init(self, key):
        kp, kv = jax.random.split(key)
        return {
            "pi": self._mlp_init(kp, (self.obs_dim, *self.hiddens, self.out_dim), final_scale=0.01),
            "vf": self._mlp_init(kv, (self.obs_dim, *self.hiddens, 1), final_scale=1.0),
        }

    @staticmethod
    def _mlp_apply(layers, x):
        for layer in layers[:-1]:
            x = jnp.tanh(x @ layer["w"] + layer["b"])
        last = layers[-1]
        return x @ last["w"] + last["b"]

    def forward(self, params, obs):
        obs = obs.reshape(obs.shape[0], -1).astype(jnp.float32)
        return {
            "action_dist_inputs": self._mlp_apply(params["pi"], obs),
            "vf": self._mlp_apply(params["vf"], obs)[..., 0],
        }
