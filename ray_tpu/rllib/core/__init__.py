from ray_tpu.rllib.core.distributions import Categorical, DiagGaussian  # noqa: F401
from ray_tpu.rllib.core.learner import Learner, LearnerGroup  # noqa: F401
from ray_tpu.rllib.core.rl_module import MLPModule, RLModule, RLModuleSpec  # noqa: F401
