"""Offline RL datasets: episode JSONL in/out.

Reference parity: rllib/offline/json_writer.py + json_reader.py — env
runners write sampled episodes to JSONL shards (`config.offline_data(
output=...)`), and off-policy algorithms train from recorded experience
instead of a live env (`input_=...`). Rows are the env runner's episode
batches (obs has T+1 rows; terminated marks true ends), stored as plain
lists so any tool can read them.
"""

from __future__ import annotations

import json
import os

import numpy as np


class JsonWriter:
    """Append episode batches to sharded JSONL files."""

    def __init__(self, path: str, max_rows_per_shard: int = 5000):
        import uuid

        self.path = path
        os.makedirs(path, exist_ok=True)
        self.max_rows = max_rows_per_shard
        # unique per WRITER, not just per pid: two writers in one process
        # (sequential runs on the same path) must not append to the same
        # shard file
        self._tag = f"{os.getpid()}-{uuid.uuid4().hex[:8]}"
        self._shard = 0
        self._rows = 0
        self._f = None

    def _file(self):
        if self._f is None or self._rows >= self.max_rows:
            if self._f is not None:
                self._f.close()
                self._shard += 1
                self._rows = 0
            self._f = open(os.path.join(self.path, f"episodes-{self._tag}-{self._shard:05d}.jsonl"), "a", buffering=1)
        return self._f

    def write(self, episode_batch: dict):
        row = {
            "obs": np.asarray(episode_batch["obs"], np.float32).tolist(),
            "actions": np.asarray(episode_batch["actions"]).tolist(),
            "rewards": np.asarray(episode_batch["rewards"], np.float32).tolist(),
            "logp": np.asarray(episode_batch.get("logp", [])).tolist(),
            "terminated": bool(episode_batch.get("terminated", False)),
        }
        self._file().write(json.dumps(row) + "\n")
        self._rows += 1

    def close(self):
        if self._f is not None:
            self._f.close()
            self._f = None


class JsonReader:
    """Iterate episode batches from a JSONL file or shard directory."""

    def __init__(self, path: str):
        self.path = path

    def _files(self):
        if os.path.isdir(self.path):
            return sorted(
                os.path.join(self.path, n) for n in os.listdir(self.path) if n.endswith(".jsonl")
            )
        return [self.path]

    def __iter__(self):
        for fp in self._files():
            with open(fp) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    row = json.loads(line)
                    yield {
                        "obs": np.asarray(row["obs"], np.float32),
                        "actions": np.asarray(row["actions"]),
                        "rewards": np.asarray(row["rewards"], np.float32),
                        "logp": np.asarray(row.get("logp", []), np.float32),
                        "terminated": bool(row.get("terminated", False)),
                    }


def write_episodes(path: str, episode_batches: list):
    w = JsonWriter(path)
    for b in episode_batches:
        w.write(b)
    w.close()


def read_episodes(path: str) -> list:
    return list(JsonReader(path))
