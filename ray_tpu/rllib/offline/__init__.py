from ray_tpu.rllib.offline.json_io import JsonReader, JsonWriter, read_episodes, write_episodes

__all__ = ["JsonReader", "JsonWriter", "read_episodes", "write_episodes"]
