"""DQN (reference: rllib/algorithms/dqn/dqn.py DQNConfig + training_step;
loss in rllib/algorithms/dqn/torch/dqn_torch_learner.py).

Off-policy Q-learning over an episode replay buffer: env runners fill the
buffer continuously; the learner draws uniform or prioritized transition
batches and takes jitted double-Q TD steps against a periodically-synced
target network. Exploration is epsilon-greedy in the env runner (the
Q-module's action "distribution").
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.core.rl_module import MLPModule
from ray_tpu.rllib.utils.replay_buffers import EpisodeReplayBuffer, PrioritizedEpisodeReplayBuffer


def make_epsilon_greedy(epsilon: float):
    """Epsilon-greedy as a distribution over Q-values (the runner's
    sample() hook; reference: EpsilonGreedy exploration)."""

    class EpsilonGreedy:
        eps = float(epsilon)

        @staticmethod
        def sample(key, q_values):
            k1, k2 = jax.random.split(key)
            greedy = jnp.argmax(q_values, axis=-1)
            rand = jax.random.randint(k1, greedy.shape, 0, q_values.shape[-1])
            explore = jax.random.uniform(k2, greedy.shape) < EpsilonGreedy.eps
            return jnp.where(explore, rand, greedy)

        @staticmethod
        def logp(q_values, actions):
            n = q_values.shape[-1]
            greedy = jnp.argmax(q_values, axis=-1)
            p = jnp.where(actions == greedy, 1.0 - EpsilonGreedy.eps + EpsilonGreedy.eps / n, EpsilonGreedy.eps / n)
            return jnp.log(p)

        @staticmethod
        def deterministic(q_values):
            return jnp.argmax(q_values, axis=-1)

        @staticmethod
        def entropy(q_values):
            return jnp.zeros(q_values.shape[:-1])

    return EpsilonGreedy


class QModule(MLPModule):
    """MLP Q-network: action_dist_inputs ARE the Q-values; exploration is
    epsilon-greedy over them."""

    def __init__(self, observation_space, action_space, model_config=None):
        assert hasattr(action_space, "n"), "DQN requires a Discrete action space"
        super().__init__(observation_space, action_space, model_config)
        self.action_dist_cls = make_epsilon_greedy(self.model_config.get("epsilon", 0.1))

    def init(self, key):
        return {"q": self._mlp_init(key, (self.obs_dim, *self.hiddens, self.out_dim), final_scale=0.01)}

    def forward(self, params, obs):
        obs = obs.reshape(obs.shape[0], -1).astype(jnp.float32)
        q = self._mlp_apply(params["q"], obs)
        return {"action_dist_inputs": q, "vf": jnp.max(q, axis=-1)}


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 5e-4
        self.train_batch_size = 64
        self.replay_buffer_capacity = 50_000
        self.prioritized_replay = False
        self.prioritized_alpha = 0.6
        self.prioritized_beta = 0.4
        self.num_steps_sampled_before_learning_starts = 500
        self.target_network_update_freq = 500  # env steps between target syncs
        self.double_q = True
        # epsilon-greedy schedule: linear initial -> final over
        # epsilon_timesteps env steps (reference: DQNConfig.epsilon
        # [[0, 1.0], [10000, 0.05]] piecewise schedule)
        self.initial_epsilon = 1.0
        self.final_epsilon = 0.05
        self.epsilon_timesteps = 10_000
        self.rollout_fragment_length = 64
        self.train_intensity = 2.0  # learner sgd steps per env step / batch size
        # offline mode (config.offline_data(input_=path)): TD updates per
        # train() iteration drawn from the recorded dataset; env runners
        # only evaluate (explore=False)
        self.offline_updates_per_iter = 50
        self.module_class = QModule

    @property
    def algo_class(self):
        return DQN


class DQNLearner(Learner):
    """Jitted (double-)Q TD step against target params. The target tree is
    an ARGUMENT of the jitted grad (it changes across updates), not a
    closure capture."""

    def _td_core(self, params, target_params, batch):
        """Shared (double-)Q TD computation: returns (q [B, A], q_taken,
        td, weighted td loss). CQL reuses this verbatim and adds its
        penalty — ONE definition of the TD math."""
        cfg = self.config
        q = self.module.forward(params, batch["obs"])["action_dist_inputs"]
        q_taken = jnp.take_along_axis(q, batch["actions"][:, None].astype(jnp.int32), axis=-1)[:, 0]
        q_next_target = self.module.forward(target_params, batch["next_obs"])["action_dist_inputs"]
        if cfg.double_q:
            # online net picks the argmax, target net evaluates it
            q_next_online = self.module.forward(params, batch["next_obs"])["action_dist_inputs"]
            next_a = jnp.argmax(q_next_online, axis=-1)
            q_next = jnp.take_along_axis(q_next_target, next_a[:, None], axis=-1)[:, 0]
        else:
            q_next = jnp.max(q_next_target, axis=-1)
        target = batch["rewards"] + cfg.gamma * (1.0 - batch["done"]) * jax.lax.stop_gradient(q_next)
        td = q_taken - target
        weights = batch.get("weights", jnp.ones_like(td))  # prioritized IS correction
        return q, q_taken, td, jnp.mean(weights * jnp.square(td))

    def build(self, seed: int = 0):
        super().build(seed)
        self.target_params = jax.tree.map(jnp.array, self.params)

        def td_loss(params, target_params, batch):
            _, q_taken, td, loss = self._td_core(params, target_params, batch)
            return loss, {"total_loss": loss, "qf_mean": jnp.mean(q_taken), "td_abs": jnp.abs(td)}

        self._td_grad = jax.jit(jax.grad(td_loss, has_aux=True))

    def update_dqn(self, batch: dict) -> tuple[dict, np.ndarray]:
        """One TD step; returns (metrics, |td| per row for priorities)."""
        mb = {k: jnp.asarray(v) for k, v in batch.items() if k != "batch_indices"}
        grads, aux = self._td_grad(self.params, self.target_params, mb)
        grads = self._sync_grads(grads)
        self.params, self.opt_state = self._apply_fn(self.params, self.opt_state, grads)
        td_abs = np.asarray(aux.pop("td_abs"))
        return {k: float(v) for k, v in aux.items()}, td_abs

    def sync_target(self):
        self.target_params = jax.tree.map(jnp.array, self.params)

    def get_state(self) -> dict:
        state = super().get_state()
        state["target_params"] = jax.tree.map(np.asarray, self.target_params)
        return state

    def set_state(self, state: dict):
        super().set_state(state)
        if "target_params" in state:
            self.target_params = jax.tree.map(jnp.asarray, state["target_params"])


class DQN(Algorithm):
    learner_cls = DQNLearner
    supports_offline_input = True

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self._total_env_steps / max(1, cfg.epsilon_timesteps))
        return cfg.initial_epsilon + frac * (cfg.final_epsilon - cfg.initial_epsilon)

    def setup(self):
        cfg = self.config
        cfg.model = {**cfg.model, "epsilon": cfg.initial_epsilon}
        if cfg.num_learners > 0:
            raise NotImplementedError("DQN runs a single (local) learner; scale sampling with num_env_runners")
        super().setup()
        if cfg.prioritized_replay:
            self.replay = PrioritizedEpisodeReplayBuffer(
                cfg.replay_buffer_capacity, alpha=cfg.prioritized_alpha, beta=cfg.prioritized_beta, seed=cfg.seed
            )
        else:
            self.replay = EpisodeReplayBuffer(cfg.replay_buffer_capacity, seed=cfg.seed)
        self._steps_since_target_sync = 0
        self._offline = bool(cfg.input_)
        if self._offline:
            # fixed-dataset training (reference: offline DQN over
            # offline/json_reader.py input): fill the buffer once
            from ray_tpu.rllib.offline import JsonReader

            n = 0
            for episode in JsonReader(cfg.input_):
                n += len(self.replay.add(episode))
                if n > self.replay.capacity:
                    raise ValueError(
                        f"offline dataset {cfg.input_!r} exceeds replay_buffer_capacity "
                        f"({self.replay.capacity}): the ring would silently drop early "
                        "transitions — raise replay_buffer_capacity to at least the dataset size"
                    )
            if n == 0:
                raise ValueError(f"offline input {cfg.input_!r} contained no transitions")
            self._offline_transitions = n

    @property
    def _learner(self) -> DQNLearner:
        return self.learner_group._local

    def training_step(self) -> dict:
        if self._offline:
            return self._offline_training_step()
        cfg = self.config
        eps = self._epsilon()
        self.env_runner_group.set_exploration(eps=eps)
        segments, runner_metrics = self.env_runner_group.sample(cfg.rollout_fragment_length)
        row_ids = []
        for seg in segments:
            row_ids.extend(self.replay.add(seg))
        new_steps = len(row_ids)
        self._total_env_steps += new_steps
        self._steps_since_target_sync += new_steps

        result = self._merge_runner_metrics(runner_metrics)
        if self._total_env_steps < cfg.num_steps_sampled_before_learning_starts or len(self.replay) < cfg.train_batch_size:
            # warmup: no update ran, so weights are unchanged — skip the
            # (potentially multi-actor) re-broadcast
            result["learner"] = {"num_updates": 0}
            result["epsilon"] = eps
            return result

        num_updates = max(1, int(new_steps * cfg.train_intensity / cfg.train_batch_size))
        metrics = {}
        for _ in range(num_updates):
            batch = self.replay.sample(cfg.train_batch_size)
            metrics, td_abs = self._learner.update_dqn(batch)
            if cfg.prioritized_replay:
                self.replay.update_priorities(batch["batch_indices"], td_abs)
        if self._steps_since_target_sync >= cfg.target_network_update_freq:
            self._learner.sync_target()
            self._steps_since_target_sync = 0
        self.env_runner_group.sync_weights(self.learner_group.get_weights())
        result["learner"] = {"num_updates": num_updates, **metrics}
        result["num_env_steps_sampled_lifetime"] = self._total_env_steps
        result["epsilon"] = eps
        return result

    def _offline_training_step(self) -> dict:
        """Train from the recorded dataset; the env (if any) is used for
        greedy EVALUATION only — no new experience enters the buffer."""
        cfg = self.config
        metrics = {}
        for _ in range(cfg.offline_updates_per_iter):
            batch = self.replay.sample(cfg.train_batch_size)
            metrics, td_abs = self._learner.update_dqn(batch)
            if cfg.prioritized_replay:
                self.replay.update_priorities(batch["batch_indices"], td_abs)
            self._steps_since_target_sync += 1
            if self._steps_since_target_sync >= max(1, cfg.target_network_update_freq // cfg.train_batch_size):
                self._learner.sync_target()
                self._steps_since_target_sync = 0
        self.env_runner_group.sync_weights(self.learner_group.get_weights())
        self.env_runner_group.set_exploration(eps=0.0)
        _, runner_metrics = self.env_runner_group.sample(cfg.rollout_fragment_length, explore=False)
        result = self._merge_runner_metrics(runner_metrics)
        result["learner"] = {"num_updates": cfg.offline_updates_per_iter, **metrics}
        result["offline_transitions"] = self._offline_transitions
        return result
