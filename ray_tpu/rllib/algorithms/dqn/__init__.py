from ray_tpu.rllib.algorithms.dqn.dqn import DQN, DQNConfig, DQNLearner, QModule

__all__ = ["DQN", "DQNConfig", "DQNLearner", "QModule"]
