"""PPO (reference: rllib/algorithms/ppo/ppo.py:364 PPOConfig, :390
training_step; loss in rllib/algorithms/ppo/torch/ppo_torch_learner.py).

Clipped-surrogate PPO with GAE. The learner update is one jitted
grad+apply per minibatch; sampling stays on CPU env runners. Advantages
are standardized over the train batch (reference's
standardize_fields=["advantages"])."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner


class PPOConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 3e-4
        self.lambda_ = 0.95
        self.clip_param = 0.2
        self.vf_clip_param = 10.0
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.0
        self.num_epochs = 10
        self.minibatch_size = 128
        self.train_batch_size = 2000
        self.grad_clip = 0.5

    @property
    def algo_class(self):
        return PPO


class PPOLearner(Learner):
    def compute_losses(self, params, batch):
        cfg = self.config
        out = self.module.forward_train(params, batch)
        dist = self.module.action_dist_cls
        inputs = out["action_dist_inputs"]
        logp = dist.logp(inputs, batch["actions"])
        ratio = jnp.exp(logp - batch["logp"])
        adv = batch["advantages"]
        surrogate = jnp.minimum(ratio * adv, jnp.clip(ratio, 1 - cfg.clip_param, 1 + cfg.clip_param) * adv)
        policy_loss = -jnp.mean(surrogate)

        vf = out["vf"]
        vf_err = (vf - batch["value_targets"]) ** 2
        vf_clipped = batch["vf_preds"] + jnp.clip(vf - batch["vf_preds"], -cfg.vf_clip_param, cfg.vf_clip_param)
        vf_err_clipped = (vf_clipped - batch["value_targets"]) ** 2
        vf_loss = 0.5 * jnp.mean(jnp.maximum(vf_err, vf_err_clipped))

        entropy = jnp.mean(dist.entropy(inputs))
        total = policy_loss + cfg.vf_loss_coeff * vf_loss - cfg.entropy_coeff * entropy
        return total, {
            "total_loss": total,
            "policy_loss": policy_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
            "mean_kl": jnp.mean(batch["logp"] - logp),
        }


class PPO(Algorithm):
    learner_cls = PPOLearner

    def setup(self):
        super().setup()
        module = self.module_spec.build()
        self._vf_fwd = jax.jit(lambda p, o: module.forward(p, o)["vf"])

    def training_step(self) -> dict:
        cfg = self.config
        segments, runner_metrics = self.env_runner_group.sample(cfg.train_batch_size)
        self._total_env_steps += sum(len(s["actions"]) for s in segments)

        params = self.learner_group.get_weights()
        batch = self._build_train_batch(segments, params)
        learner_metrics = self.learner_group.update(
            batch, minibatch_size=cfg.minibatch_size, num_epochs=cfg.num_epochs, seed=cfg.seed + self.iteration
        )
        self.env_runner_group.sync_weights(self.learner_group.get_weights())

        result = self._merge_runner_metrics(runner_metrics)
        result["learners"] = {k: float(np.mean([m[k] for m in learner_metrics])) for k in learner_metrics[0]}
        return result

    def _build_train_batch(self, segments: list[dict], params) -> dict:
        """GAE (generalized advantage estimation) per segment, then a flat
        row-batch. Bootstrap value for cut/truncated segments comes from a
        forward pass on each segment's final obs."""
        cfg = self.config
        boot_obs = np.stack([s["obs"][-1] for s in segments])
        boot_vals = np.asarray(self._vf_fwd(params, jnp.asarray(boot_obs)))
        obs, actions, logp, advs, targets, vf_preds = [], [], [], [], [], []
        for s, bv in zip(segments, boot_vals):
            T = len(s["actions"])
            v = s["vf_preds"]
            # final v_next is 0 past a terminal, else the bootstrap value
            v_next = np.append(v[1:], 0.0 if s["terminated"] else bv)
            delta = s["rewards"] + cfg.gamma * v_next - v
            adv = np.zeros(T, dtype=np.float32)
            acc = 0.0
            for t in range(T - 1, -1, -1):
                acc = delta[t] + cfg.gamma * cfg.lambda_ * acc
                adv[t] = acc
            obs.append(s["obs"][:-1])
            actions.append(s["actions"])
            logp.append(s["logp"])
            vf_preds.append(v)
            advs.append(adv)
            targets.append(adv + v)
        adv_all = np.concatenate(advs)
        adv_all = (adv_all - adv_all.mean()) / (adv_all.std() + 1e-8)
        return {
            "obs": np.concatenate(obs),
            "actions": np.concatenate(actions),
            "logp": np.concatenate(logp),
            "advantages": adv_all.astype(np.float32),
            "value_targets": np.concatenate(targets).astype(np.float32),
            "vf_preds": np.concatenate(vf_preds).astype(np.float32),
        }
