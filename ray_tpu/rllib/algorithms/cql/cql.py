"""CQL: conservative Q-learning for offline RL.

Reference parity: rllib/algorithms/cql/cql.py:1 (CQLConfig; the
conservative regularizer of cql_torch_learner). Discrete-action form
over the DQN machinery: the TD loss gains the CQL(H) penalty

    alpha_cql * E_s[ logsumexp_a Q(s, a) - Q(s, a_data) ]

which pushes DOWN Q-values of actions absent from the dataset (the
out-of-distribution overestimation that breaks naive offline DQN) while
pushing UP the logged actions'. Offline-only: input_ is required and env
runners evaluate greedily.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ray_tpu.rllib.algorithms.dqn.dqn import DQN, DQNConfig, DQNLearner


class CQLConfig(DQNConfig):
    def __init__(self):
        super().__init__()
        self.cql_alpha = 1.0  # conservative penalty weight
        self.lr = 5e-4

    @property
    def algo_class(self):
        return CQL


class CQLLearner(DQNLearner):
    """The DQN TD step (shared _td_core — incl. prioritized IS weights)
    plus the conservative penalty, still ONE jitted grad."""

    def build(self, seed: int = 0):
        super().build(seed)
        cfg = self.config

        def cql_loss(params, target_params, batch):
            q, q_taken, td, td_loss = self._td_core(params, target_params, batch)
            # conservative regularizer: logsumexp over ALL actions minus
            # the dataset action's Q — OOD actions get pushed down
            conservative = jnp.mean(jax.scipy.special.logsumexp(q, axis=-1) - q_taken)
            loss = td_loss + cfg.cql_alpha * conservative
            return loss, {
                "total_loss": loss,
                "td_loss": td_loss,
                "cql_penalty": conservative,
                "qf_mean": jnp.mean(q_taken),
                "td_abs": jnp.abs(td),
            }

        self._td_grad = jax.jit(jax.grad(cql_loss, has_aux=True))


class CQL(DQN):
    learner_cls = CQLLearner
    supports_offline_input = True

    def setup(self):
        if not self.config.input_:
            raise ValueError("CQL is offline-only: configure offline_data(input_=<episode dataset path>)")
        super().setup()
