"""IMPALA (reference: rllib/algorithms/impala/impala.py — async off-policy
actor-learner with V-trace correction; multi-learner via DDP there, via
ray_tpu.collective grad-allreduce here).

The learner consumes fixed-length [N, T] trajectory sequences; V-trace
targets (Espeholt et al. 2018) are computed *inside* the jitted loss with
a reversed lax.scan, so the whole update stays one XLA program. Sampling
overlaps learning one iteration deep (in-flight sample refs), the
synchronous-queue shape of the reference's aggregator-less small config.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

import ray_tpu
from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner


class IMPALAConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 5e-4
        self.vf_loss_coeff = 0.5
        self.entropy_coeff = 0.01
        self.rho_clip = 1.0
        self.c_clip = 1.0
        self.rollout_fragment_length = 50
        self.train_batch_size = 500
        self.grad_clip = 40.0
        self.num_epochs = 1
        self.minibatch_size = None

    @property
    def algo_class(self):
        return IMPALA


def vtrace(behavior_logp, target_logp, rewards, values, bootstrap_value, mask, nonterminal, gamma, rho_clip, c_clip):
    """V-trace targets + policy-gradient advantages over [N, T] sequences.

    values: [N, T] current value estimates; bootstrap_value: [N];
    nonterminal: [N, T] — 0 where the transition at t enters a terminal
    state (so no value bootstraps across an episode boundary, wherever in
    the fragment it falls). Returns (vs [N,T], pg_advantages [N,T]);
    padded steps (mask==0) pass through their value estimate.
    """
    rho = jnp.exp(target_logp - behavior_logp)
    rho_bar = jnp.minimum(rho_clip, rho) * mask
    c_bar = jnp.minimum(c_clip, rho) * mask
    v_next = jnp.concatenate([values[:, 1:], bootstrap_value[:, None]], axis=1) * nonterminal
    delta = rho_bar * (rewards + gamma * v_next - values)

    def body(carry, xs):
        d_t, c_t, nt_t = xs
        # carry = vs_{t+1} - V(x_{t+1}); a terminal at t cuts the recursion
        vs_minus_v = d_t + gamma * c_t * nt_t * carry
        return vs_minus_v, vs_minus_v

    xs = (delta.T, c_bar.T, nonterminal.T)  # scan over time, reversed
    _, out = jax.lax.scan(body, jnp.zeros(values.shape[0]), xs, reverse=True)
    vs = values + out.T
    vs_next = jnp.concatenate([vs[:, 1:], bootstrap_value[:, None]], axis=1) * nonterminal
    pg_adv = rho_bar * (rewards + gamma * vs_next - values)
    return jax.lax.stop_gradient(vs), jax.lax.stop_gradient(pg_adv)


class IMPALALearner(Learner):
    def compute_losses(self, params, batch):
        cfg = self.config
        N, T = batch["rewards"].shape
        obs_flat = batch["obs"].reshape((N * (T + 1),) + batch["obs"].shape[2:])
        out = self.module.forward(params, obs_flat)
        dist = self.module.action_dist_cls
        inputs = out["action_dist_inputs"].reshape(N, T + 1, -1)[:, :-1]
        values_all = out["vf"].reshape(N, T + 1)
        values, bootstrap = values_all[:, :-1], values_all[:, -1]

        target_logp = dist.logp(inputs, batch["actions"])
        mask = batch["mask"]
        vs, pg_adv = vtrace(
            batch["logp"],
            target_logp,
            batch["rewards"],
            values,
            bootstrap,
            mask,
            batch["nonterminal"],
            cfg.gamma,
            cfg.rho_clip,
            cfg.c_clip,
        )
        denom = jnp.maximum(jnp.sum(mask), 1.0)
        policy_loss = -jnp.sum(target_logp * pg_adv * mask) / denom
        vf_loss = 0.5 * jnp.sum(((vs - values) ** 2) * mask) / denom
        entropy = jnp.sum(dist.entropy(inputs) * mask) / denom
        total = policy_loss + cfg.vf_loss_coeff * vf_loss - cfg.entropy_coeff * entropy
        return total, {"total_loss": total, "policy_loss": policy_loss, "vf_loss": vf_loss, "entropy": entropy}


class IMPALA(Algorithm):
    learner_cls = IMPALALearner

    def setup(self):
        super().setup()
        self._inflight = None  # one-iteration-deep sample pipeline

    def training_step(self) -> dict:
        cfg = self.config
        if self._inflight is not None:
            segments, runner_metrics = self.env_runner_group.collect(self._inflight)
            self._inflight = None
        else:
            segments, runner_metrics = self.env_runner_group.sample(cfg.train_batch_size)
        if cfg.num_env_runners > 0:
            # off-policy: next iteration's sample runs on current (soon
            # stale) weights while the learners update — V-trace corrects
            self._inflight = self.env_runner_group.sample_async(cfg.train_batch_size)
        self._total_env_steps += sum(len(s["actions"]) for s in segments)
        batch = self._build_sequences(segments)
        learner_metrics = self.learner_group.update(batch, num_epochs=cfg.num_epochs, shuffle=False)
        self.env_runner_group.sync_weights(self.learner_group.get_weights())
        result = self._merge_runner_metrics(runner_metrics)
        result["learners"] = {k: float(np.mean([m[k] for m in learner_metrics])) for k in learner_metrics[0]}
        return result

    def _build_sequences(self, segments: list[dict]) -> dict:
        """Chunk segments into fragments of rollout_fragment_length and pad
        -> [N, T(+1)] arrays. Nothing is discarded: a segment longer than T
        becomes multiple rows, each bootstrapping from its own next obs.
        `nonterminal[i, t] == 0` marks a transition into a terminal state
        (only ever the last real step of a fragment)."""
        T = self.config.rollout_fragment_length
        chunks = []  # (segment, start, length, is_final_chunk)
        for s in segments:
            n = len(s["actions"])
            for t0 in range(0, n, T):
                t1 = min(t0 + T, n)
                chunks.append((s, t0, t1 - t0, t1 == n))
        obs_shape = segments[0]["obs"].shape[1:]
        N = len(chunks)
        obs = np.zeros((N, T + 1) + obs_shape, np.float32)
        actions = np.zeros((N, T) + segments[0]["actions"].shape[1:], segments[0]["actions"].dtype)
        rewards = np.zeros((N, T), np.float32)
        logp = np.zeros((N, T), np.float32)
        mask = np.zeros((N, T), np.float32)
        nonterminal = np.ones((N, T), np.float32)
        for i, (s, t0, t, final) in enumerate(chunks):
            obs[i, : t + 1] = s["obs"][t0 : t0 + t + 1]
            obs[i, t + 1 :] = s["obs"][t0 + t]  # repeat last obs into padding
            actions[i, :t] = s["actions"][t0 : t0 + t]
            rewards[i, :t] = s["rewards"][t0 : t0 + t]
            logp[i, :t] = s["logp"][t0 : t0 + t]
            mask[i, :t] = 1.0
            if final and bool(s["terminated"]):
                nonterminal[i, t - 1] = 0.0
        return {"obs": obs, "actions": actions, "rewards": rewards, "logp": logp, "mask": mask, "nonterminal": nonterminal}
