"""Algorithm: the RL training driver (reference:
rllib/algorithms/algorithm.py:212 — step() :1189 delegating to per-algo
training_step() :2273; EnvRunnerGroup + LearnerGroup topology).

Holds the sampling/learning topology; per-algo subclasses implement
`training_step()` and declare their Learner class. Checkpointable via
save/restore of learner state (reference Checkpointable mixin,
rllib/utils/checkpoints.py).
"""

from __future__ import annotations

import pickle
import time
from pathlib import Path

import numpy as np

from ray_tpu.rllib.core.rl_module import MLPModule, RLModuleSpec
from ray_tpu.rllib.env.env_runner import EnvRunnerGroup


class Algorithm:
    learner_cls: type = None  # set by subclasses
    supports_offline_input = False  # DQN-family overrides

    def __init__(self, config):
        self.config = config
        self.iteration = 0
        self._total_env_steps = 0
        self.setup()

    # -- topology --
    def setup(self):
        cfg = self.config
        assert cfg.env is not None, "config.environment(env=...) is required"
        if cfg.input_ and not type(self).supports_offline_input:
            raise NotImplementedError(
                f"{type(self).__name__} does not support offline_data(input_=...); "
                "use an off-policy algorithm (DQN)"
            )
        if cfg.input_ and cfg.output:
            raise ValueError(
                "offline_data(input_=..., output=...) conflict: offline mode "
                "evaluates greedily and recording those episodes would pollute "
                "the dataset — drop output for offline training"
            )
        # spaces come from a throwaway env (cheap for gym registry ids)
        import gymnasium as gym

        probe = gym.make(cfg.env, **cfg.env_config)
        obs_space, act_space = probe.observation_space, probe.action_space
        probe.close()
        self.module_spec = RLModuleSpec(cfg.module_class or MLPModule, obs_space, act_space, cfg.model)

        self.env_runner_group = EnvRunnerGroup(
            self.module_spec,
            cfg.env,
            cfg.env_config,
            num_env_runners=cfg.num_env_runners,
            num_envs_per_env_runner=cfg.num_envs_per_env_runner,
            seed=cfg.seed,
            output=cfg.output,  # input_+output conflicts rejected above
            env_to_module=cfg.env_to_module_connector,
            module_to_env=cfg.module_to_env_connector,
        )
        from ray_tpu.rllib.core.learner import LearnerGroup

        self.learner_group = LearnerGroup(type(self).learner_cls, self.module_spec, cfg, num_learners=cfg.num_learners)
        self.env_runner_group.sync_weights(self.learner_group.get_weights())

    # -- public API --
    def train(self) -> dict:
        t0 = time.perf_counter()
        self.iteration += 1
        result = self.training_step()
        result.setdefault("training_iteration", self.iteration)
        result.setdefault("time_this_iter_s", time.perf_counter() - t0)
        result.setdefault("num_env_steps_sampled_lifetime", self._total_env_steps)
        return result

    def training_step(self) -> dict:
        raise NotImplementedError

    def stop(self):
        self.env_runner_group.stop()
        self.learner_group.stop()

    # -- checkpointing --
    def save_to_path(self, path: str) -> str:
        p = Path(path)
        p.mkdir(parents=True, exist_ok=True)
        state = {
            "iteration": self.iteration,
            "total_env_steps": self._total_env_steps,
            "learner": self.learner_group.get_state(),
        }
        with open(p / "algorithm_state.pkl", "wb") as f:
            pickle.dump(state, f)
        return str(p)

    def restore_from_path(self, path: str):
        with open(Path(path) / "algorithm_state.pkl", "rb") as f:
            state = pickle.load(f)
        self.iteration = state["iteration"]
        self._total_env_steps = state["total_env_steps"]
        self.learner_group.set_state(state["learner"])
        self.env_runner_group.sync_weights(self.learner_group.get_weights())

    # -- shared helpers --
    def _require_offline_only(self):
        """Guard for offline-only algorithms (BC/MARWIL; the reference
        encodes this by subclassing — bc.py BCConfig validates input_)."""
        cfg = self.config
        if not cfg.input_:
            raise ValueError(
                f"{type(self).__name__} is offline-only: configure "
                "offline_data(input_=<episode dataset path>)"
            )
        if cfg.num_learners > 0:
            raise NotImplementedError(f"{type(self).__name__} runs a single (local) learner")

    def _offline_eval_result(self, learner_metrics: dict, num_updates: int) -> dict:
        """Tail of an offline training_step: push weights, evaluate the
        policy GREEDILY (no exploration data ever enters offline
        training), and package the result dict."""
        self.env_runner_group.sync_weights(self.learner_group.get_weights())
        _, runner_metrics = self.env_runner_group.sample(self.config.rollout_fragment_length, explore=False)
        result = self._merge_runner_metrics(runner_metrics)
        result["learner"] = {"num_updates": num_updates, **learner_metrics}
        return result

    def _merge_runner_metrics(self, metrics: list[dict]) -> dict:
        returns = [m["episode_return_mean"] for m in metrics if np.isfinite(m.get("episode_return_mean", float("nan")))]
        return {
            "env_runners": {
                "episode_return_mean": float(np.mean(returns)) if returns else float("nan"),
                "num_episodes": int(sum(m.get("num_episodes", 0) for m in metrics)),
            }
        }
