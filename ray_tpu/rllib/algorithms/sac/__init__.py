from ray_tpu.rllib.algorithms.sac.sac import SAC, SACConfig, SACModule

__all__ = ["SAC", "SACConfig", "SACModule"]
