"""SAC: soft actor-critic for continuous control.

Reference parity: rllib/algorithms/sac/sac.py:1 (SACConfig +
training_step) with the loss structure of
rllib/algorithms/sac/torch/sac_torch_learner.py — twin Q networks with
polyak-averaged targets, a tanh-squashed gaussian policy, and
automatically tuned entropy temperature (target entropy = -|A|).

TPU-native shape: policy, twin critics, and log_alpha live in ONE param
pytree; a single jitted grad computes all three losses with stop_gradient
fencing (critic grads never reach pi, actor grads never reach the
critics, alpha sees only the detached logp), so one optimizer step and
one polyak map per update — no per-tower optimizer plumbing, and the
whole update is one XLA program.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.distributions import DiagGaussian, make_squashed_gaussian
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.core.rl_module import MLPModule
from ray_tpu.rllib.utils.replay_buffers import EpisodeReplayBuffer


class SACModule(MLPModule):
    """Policy tower (obs -> mean||log_std), twin Q towers (obs||act ->
    scalar), and the entropy temperature. action_dist_inputs are the raw
    gaussian params; the squashing lives in the distribution."""

    def __init__(self, observation_space, action_space, model_config=None):
        assert hasattr(action_space, "shape"), "SAC requires a continuous (Box) action space"
        super().__init__(observation_space, action_space, model_config)
        self.act_dim = int(np.prod(action_space.shape))
        self.action_dist_cls = make_squashed_gaussian(action_space.low, action_space.high)

    def init(self, key):
        kp, k1, k2 = jax.random.split(key, 3)
        qs = (self.obs_dim + self.act_dim, *self.hiddens, 1)
        return {
            "pi": self._mlp_init(kp, (self.obs_dim, *self.hiddens, 2 * self.act_dim), final_scale=0.01),
            "q1": self._mlp_init(k1, qs, final_scale=1.0),
            "q2": self._mlp_init(k2, qs, final_scale=1.0),
            "log_alpha": jnp.zeros(()),
        }

    def forward(self, params, obs):
        obs = obs.reshape(obs.shape[0], -1).astype(jnp.float32)
        out = self._mlp_apply(params["pi"], obs)
        return {"action_dist_inputs": out, "vf": jnp.zeros(obs.shape[0])}

    def q_values(self, q_params, obs, actions):
        obs = obs.reshape(obs.shape[0], -1).astype(jnp.float32)
        x = jnp.concatenate([obs, actions.reshape(obs.shape[0], -1).astype(jnp.float32)], axis=-1)
        return self._mlp_apply(q_params, x)[..., 0]


class SACConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 3e-4
        self.train_batch_size = 256
        self.replay_buffer_capacity = 100_000
        self.tau = 0.005  # polyak target mix-in per update
        self.initial_alpha = 0.1
        self.target_entropy = "auto"  # -> -act_dim
        self.num_steps_sampled_before_learning_starts = 1_000
        self.rollout_fragment_length = 64
        # updates per iteration = new_env_steps * train_intensity /
        # train_batch_size; the default equals batch size, i.e. ~ONE
        # gradient step per env step — SAC's standard replay ratio
        # (reference sac.py training_intensity semantics)
        self.train_intensity = 256.0
        self.module_class = SACModule

    @property
    def algo_class(self):
        return SAC


class SACLearner(Learner):
    """One jitted update: combined actor/critic/alpha grad with
    stop_gradient fencing + optimizer step + polyak target map."""

    def build(self, seed: int = 0):
        super().build(seed)
        self.params["log_alpha"] = jnp.log(jnp.asarray(self.config.initial_alpha, jnp.float32))
        self.opt_state = self.optimizer.init(self.params)
        self.target_q = {"q1": jax.tree.map(jnp.array, self.params["q1"]), "q2": jax.tree.map(jnp.array, self.params["q2"])}
        self._key = jax.random.PRNGKey(seed + 1)
        cfg = self.config
        act_dim = self.module.act_dim
        target_entropy = -float(act_dim) if cfg.target_entropy == "auto" else float(cfg.target_entropy)
        module = self.module
        dist = module.action_dist_cls

        def sample_squashed(params_pi, obs, key):
            """Reparameterized squashed sample + its logp, computed from u
            directly (no atanh round trip)."""
            out = module._mlp_apply(params_pi, obs.reshape(obs.shape[0], -1).astype(jnp.float32))
            mean, log_std = DiagGaussian._split(out)
            u = mean + jnp.exp(log_std) * jax.random.normal(key, mean.shape)
            t = jnp.tanh(u)
            scale = (dist.high - dist.low) * 0.5
            a = (dist.high + dist.low) * 0.5 + scale * t
            base = jnp.sum(-0.5 * (((u - mean) / jnp.exp(log_std)) ** 2 + 2 * log_std + jnp.log(2 * jnp.pi)), axis=-1)
            logp = base - jnp.sum(jnp.log(scale * (1.0 - t**2) + 1e-9), axis=-1)
            return a, logp

        def losses(params, target_q, batch, key):
            k1, k2 = jax.random.split(key)
            alpha = jnp.exp(params["log_alpha"])
            alpha_sg = jax.lax.stop_gradient(alpha)

            # -- critic: targets from the CURRENT policy at s', target Qs
            a2, logp2 = sample_squashed(params["pi"], batch["next_obs"], k2)
            q1_t = module.q_values(target_q["q1"], batch["next_obs"], a2)
            q2_t = module.q_values(target_q["q2"], batch["next_obs"], a2)
            soft_target = jnp.minimum(q1_t, q2_t) - alpha_sg * logp2
            y = jax.lax.stop_gradient(
                batch["rewards"] + cfg.gamma * (1.0 - batch["done"]) * soft_target
            )
            q1 = module.q_values(params["q1"], batch["obs"], batch["actions"])
            q2 = module.q_values(params["q2"], batch["obs"], batch["actions"])
            critic_loss = jnp.mean((q1 - y) ** 2) + jnp.mean((q2 - y) ** 2)

            # -- actor: maximize soft value through FROZEN critics
            q_frozen = jax.tree.map(jax.lax.stop_gradient, {"q1": params["q1"], "q2": params["q2"]})
            a_pi, logp_pi = sample_squashed(params["pi"], batch["obs"], k1)
            q_pi = jnp.minimum(
                module.q_values(q_frozen["q1"], batch["obs"], a_pi),
                module.q_values(q_frozen["q2"], batch["obs"], a_pi),
            )
            actor_loss = jnp.mean(alpha_sg * logp_pi - q_pi)

            # -- temperature: drive E[logp] toward -target_entropy
            alpha_loss = -jnp.mean(params["log_alpha"] * jax.lax.stop_gradient(logp_pi + target_entropy))

            total = critic_loss + actor_loss + alpha_loss
            return total, {
                "total_loss": total,
                "critic_loss": critic_loss,
                "actor_loss": actor_loss,
                "alpha_loss": alpha_loss,
                "alpha": alpha,
                "qf_mean": jnp.mean(q1),
                "entropy_proxy": -jnp.mean(logp_pi),
            }

        grad_fn = jax.grad(losses, has_aux=True)

        import optax

        def update(params, opt_state, target_q, batch, key):
            grads, aux = grad_fn(params, target_q, batch, key)
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            tau = cfg.tau
            target_q = jax.tree.map(
                lambda t, o: (1.0 - tau) * t + tau * o,
                target_q,
                {"q1": params["q1"], "q2": params["q2"]},
            )
            return params, opt_state, target_q, aux

        self._update_fn = jax.jit(update)

    def update_sac(self, batch: dict) -> dict:
        mb = {k: jnp.asarray(v) for k, v in batch.items() if k != "batch_indices"}
        self._key, k = jax.random.split(self._key)
        self.params, self.opt_state, self.target_q, aux = self._update_fn(
            self.params, self.opt_state, self.target_q, mb, k
        )
        return {k2: float(v) for k2, v in aux.items()}

    def get_state(self) -> dict:
        state = super().get_state()
        state["target_q"] = jax.tree.map(np.asarray, self.target_q)
        return state

    def set_state(self, state: dict):
        super().set_state(state)
        if "target_q" in state:
            self.target_q = jax.tree.map(jnp.asarray, state["target_q"])


class SAC(Algorithm):
    learner_cls = SACLearner

    def setup(self):
        cfg = self.config
        if cfg.num_learners > 0:
            raise NotImplementedError("SAC runs a single (local) learner; scale sampling with num_env_runners")
        super().setup()
        self.replay = EpisodeReplayBuffer(cfg.replay_buffer_capacity, seed=cfg.seed)

    @property
    def _learner(self) -> SACLearner:
        return self.learner_group._local

    def training_step(self) -> dict:
        cfg = self.config
        segments, runner_metrics = self.env_runner_group.sample(cfg.rollout_fragment_length)
        new_steps = 0
        for seg in segments:
            new_steps += len(self.replay.add(seg))
        self._total_env_steps += new_steps

        result = self._merge_runner_metrics(runner_metrics)
        if self._total_env_steps < cfg.num_steps_sampled_before_learning_starts or len(self.replay) < cfg.train_batch_size:
            result["learner"] = {"num_updates": 0}
            return result

        num_updates = max(1, int(new_steps * cfg.train_intensity / cfg.train_batch_size))
        metrics = {}
        for _ in range(num_updates):
            batch = self.replay.sample(cfg.train_batch_size)
            metrics = self._learner.update_sac(batch)
        self.env_runner_group.sync_weights(self.learner_group.get_weights())
        result["learner"] = {"num_updates": num_updates, **metrics}
        result["num_env_steps_sampled_lifetime"] = self._total_env_steps
        return result
