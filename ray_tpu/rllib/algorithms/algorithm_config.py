"""AlgorithmConfig builder (reference: rllib/algorithms/algorithm_config.py —
fluent .environment()/.env_runners()/.training()/.learners() chaining that
`build_algo()`s into an Algorithm)."""

from __future__ import annotations

import copy


class AlgorithmConfig:
    algo_class: type | None = None

    def __init__(self):
        # environment
        self.env: str | None = None
        self.env_config: dict = {}
        # env runners
        self.num_env_runners: int = 0
        self.num_envs_per_env_runner: int = 1
        self.rollout_fragment_length: int = 200
        # connector pipelines (rllib/connectors/; reference:
        # config.env_runners(env_to_module_connector=...))
        self.env_to_module_connector = None
        self.module_to_env_connector = None
        # learners
        self.num_learners: int = 0
        # training
        self.lr: float = 5e-4
        self.gamma: float = 0.99
        self.train_batch_size: int = 4000
        self.minibatch_size: int | None = None
        self.num_epochs: int = 1
        self.grad_clip: float | None = None
        self.model: dict = {}
        # offline data (reference: config.offline_data(input_=..., output=...))
        self.output: str | None = None  # record sampled episodes to JSONL
        self.input_: str | None = None  # train from recorded episodes
        # rl module
        self.module_class: type | None = None
        # debugging
        self.seed: int = 0

    # -- fluent sections (reference algorithm_config.py API shape) --
    def environment(self, env: str | None = None, *, env_config: dict | None = None):
        if env is not None:
            self.env = env
        if env_config is not None:
            self.env_config = dict(env_config)
        return self

    def env_runners(self, *, num_env_runners: int | None = None, num_envs_per_env_runner: int | None = None, rollout_fragment_length: int | None = None, env_to_module_connector=None, module_to_env_connector=None):
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        if env_to_module_connector is not None:
            self.env_to_module_connector = env_to_module_connector
        if module_to_env_connector is not None:
            self.module_to_env_connector = module_to_env_connector
        return self

    def learners(self, *, num_learners: int | None = None):
        if num_learners is not None:
            self.num_learners = num_learners
        return self

    def training(self, **kwargs):
        for k, v in kwargs.items():
            if not hasattr(self, k):
                raise AttributeError(f"unknown training option {k!r}")
            setattr(self, k, v)
        return self

    def offline_data(self, *, input_: str | None = None, output: str | None = None):
        if input_ is not None:
            self.input_ = input_
        if output is not None:
            self.output = output
        return self

    def rl_module(self, *, module_class: type | None = None, model_config: dict | None = None):
        if module_class is not None:
            self.module_class = module_class
        if model_config is not None:
            self.model = dict(model_config)
        return self

    def debugging(self, *, seed: int | None = None):
        if seed is not None:
            self.seed = seed
        return self

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def build_algo(self):
        assert self.algo_class is not None, "use a concrete config (PPOConfig, IMPALAConfig)"
        return self.algo_class(self.copy())

    # reference spelling kept as an alias
    build = build_algo
