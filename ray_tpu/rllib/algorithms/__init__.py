from ray_tpu.rllib.algorithms.algorithm import Algorithm  # noqa: F401
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig  # noqa: F401
