"""MARWIL: monotonic advantage re-weighted imitation learning (offline).

Reference parity: rllib/algorithms/marwil/marwil.py:1 (MARWILConfig:
beta / vf_coeff / moving-average advantage normalization) with the loss
of rllib/algorithms/marwil/torch/marwil_torch_learner.py — a value head
regresses Monte-Carlo returns of the recorded episodes, and the policy
clones dataset actions weighted by exp(beta * normalized advantage), so
better-than-baseline transitions are imitated harder. beta=0 degenerates
to plain BC (the reference's BC subclasses MARWIL for exactly this
reason; here BC stands alone and MARWIL mirrors its offline plumbing).

TPU-native shape: returns are precomputed per episode at load time (a
reversed cumulative sum on host — data prep, not model math), the whole
dataset lives as flat [M, ...] arrays, and one jitted grad covers the
policy + value losses. The advantage-normalization moving average is
host-side state threaded through the batch as a column (same trick as
APPO's kl_coeff), so the jitted loss never closes over a mutable.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner


class MARWILConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.train_batch_size = 256
        self.updates_per_iter = 50
        self.beta = 1.0  # 0 => behavior cloning
        self.vf_coeff = 1.0
        # update rate of the squared-advantage moving average used to
        # normalize exponent scale (reference marwil.py
        # moving_average_sqd_adv_norm_update_rate)
        self.ma_adv_norm_rate = 1e-2
        self.ma_adv_norm_start = 100.0

    @property
    def algo_class(self):
        return MARWIL


class MARWILLearner(Learner):
    def build(self, seed: int = 0):
        super().build(seed)
        self.ma_adv_norm = float(self.config.ma_adv_norm_start)

    def compute_losses(self, params, batch):
        cfg = self.config
        out = self.module.forward_train(params, batch)
        logp = self.module.action_dist_cls.logp(out["action_dist_inputs"], batch["actions"])
        adv = batch["returns"] - out["vf"]
        vf_loss = jnp.mean(adv**2)
        # exponent uses the running scale, not the per-batch one, so the
        # weighting is stable across minibatches (reference learner's
        # update_averaged_weights); clip the exponent for safety
        scale = jax.lax.rsqrt(jnp.maximum(batch["ma_adv_norm"][0], 1e-8))
        weights = jnp.exp(jnp.clip(cfg.beta * jax.lax.stop_gradient(adv) * scale, -20.0, 20.0))
        policy_loss = -jnp.mean(weights * logp)
        total = policy_loss + cfg.vf_coeff * vf_loss
        return total, {
            "total_loss": total,
            "policy_loss": policy_loss,
            "vf_loss": vf_loss,
            "mean_sqd_adv": jnp.mean(jax.lax.stop_gradient(adv) ** 2),
            "mean_weight": jnp.mean(weights),
        }

    def update_marwil(self, batch: dict) -> dict:
        batch = dict(batch)
        batch["ma_adv_norm"] = np.full((len(batch["returns"]),), self.ma_adv_norm, np.float32)
        metrics = self.update(batch)
        rate = self.config.ma_adv_norm_rate
        self.ma_adv_norm += rate * (metrics["mean_sqd_adv"] - self.ma_adv_norm)
        metrics["ma_adv_norm"] = self.ma_adv_norm
        return metrics

    def get_state(self) -> dict:
        state = super().get_state()
        state["ma_adv_norm"] = self.ma_adv_norm
        return state

    def set_state(self, state: dict):
        super().set_state(state)
        self.ma_adv_norm = float(state.get("ma_adv_norm", self.ma_adv_norm))


class MARWIL(Algorithm):
    learner_cls = MARWILLearner
    supports_offline_input = True

    def setup(self):
        cfg = self.config
        self._require_offline_only()
        super().setup()
        from ray_tpu.rllib.offline import JsonReader

        obs_rows, act_rows, ret_rows = [], [], []
        for ep in JsonReader(cfg.input_):
            rewards = np.asarray(ep["rewards"], np.float32)
            T = len(rewards)
            if T == 0:
                continue
            # Monte-Carlo return-to-go; an episode cut by the horizon (not
            # terminated) still uses its observed return — offline data has
            # no bootstrap target (reference marwil postprocessing)
            returns = np.zeros(T, np.float32)
            acc = 0.0
            for t in range(T - 1, -1, -1):
                acc = rewards[t] + cfg.gamma * acc
                returns[t] = acc
            obs_rows.append(np.asarray(ep["obs"], np.float32)[:T])
            act_rows.append(np.asarray(ep["actions"]))
            ret_rows.append(returns)
        if not obs_rows:
            raise ValueError(f"offline input {cfg.input_!r} contained no transitions")
        self._obs = np.concatenate(obs_rows)
        self._actions = np.concatenate(act_rows)
        self._returns = np.concatenate(ret_rows)
        self._rng = np.random.default_rng(cfg.seed)

    @property
    def _learner(self) -> MARWILLearner:
        return self.learner_group._local

    def training_step(self) -> dict:
        cfg = self.config
        metrics: dict = {}
        for _ in range(cfg.updates_per_iter):
            idx = self._rng.integers(0, len(self._returns), cfg.train_batch_size)
            batch = {"obs": self._obs[idx], "actions": self._actions[idx], "returns": self._returns[idx]}
            metrics = self._learner.update_marwil(batch)
        result = self._offline_eval_result(metrics, cfg.updates_per_iter)
        result["dataset_transitions"] = int(len(self._returns))
        return result
