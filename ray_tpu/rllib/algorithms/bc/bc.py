"""BC: behavior cloning from recorded episodes.

Reference parity: rllib/algorithms/bc/bc.py:1 (BCConfig, offline-only
training via the offline data pipeline; loss is the negative log
likelihood of the dataset actions — bc_torch_learner). Works on any
module whose action distribution exposes logp: discrete (Categorical
logits) and continuous (DiagGaussian) both clone.

Offline-only by definition: ``config.offline_data(input_=path)`` is
required; env runners (if an env is configured) only evaluate the cloned
policy greedily — no exploration data ever enters training.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm import Algorithm
from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.core.learner import Learner
from ray_tpu.rllib.utils.replay_buffers import EpisodeReplayBuffer


class BCConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.lr = 1e-3
        self.train_batch_size = 256
        self.updates_per_iter = 50
        self.replay_buffer_capacity = 1_000_000

    @property
    def algo_class(self):
        return BC


class BCLearner(Learner):
    """Supervised: maximize logp of dataset actions under the policy."""

    def compute_losses(self, params, batch):
        out = self.module.forward_train(params, batch)
        logp = self.module.action_dist_cls.logp(out["action_dist_inputs"], batch["actions"])
        loss = -jnp.mean(logp)
        return loss, {"total_loss": loss, "bc_logp_mean": jnp.mean(logp)}


class BC(Algorithm):
    learner_cls = BCLearner
    supports_offline_input = True

    def setup(self):
        cfg = self.config
        self._require_offline_only()
        super().setup()
        from ray_tpu.rllib.offline import JsonReader

        self.replay = EpisodeReplayBuffer(cfg.replay_buffer_capacity, seed=cfg.seed)
        n = 0
        for episode in JsonReader(cfg.input_):
            n += len(self.replay.add(episode))
        if n == 0:
            raise ValueError(f"offline input {cfg.input_!r} contained no transitions")
        self._dataset_transitions = n

    @property
    def _learner(self) -> BCLearner:
        return self.learner_group._local

    def training_step(self) -> dict:
        cfg = self.config
        metrics: dict = {}
        for _ in range(cfg.updates_per_iter):
            batch = self.replay.sample(cfg.train_batch_size)
            metrics = self._learner.update(batch)
        result = self._offline_eval_result(metrics, cfg.updates_per_iter)
        result["dataset_transitions"] = self._dataset_transitions
        return result
