"""APPO: asynchronous PPO — IMPALA's actor/learner architecture with a
PPO clipped-surrogate loss anchored on a periodically-refreshed target
("old") policy.

Reference parity: rllib/algorithms/appo/appo.py:1 (APPOConfig:
clip_param / use_kl_loss / kl_coeff / kl_target / tau /
target_network_update_freq) with the loss structure of
rllib/algorithms/appo/torch/appo_torch_learner.py — V-trace importance
weights are computed between the BEHAVIOR policy (the sampler's logp,
possibly several updates stale) and the TARGET policy, and the PPO ratio
is the current/behavior ratio re-anchored onto the target policy via a
clipped IS correction (the IMPACT estimator, Luo et al. 2020).

TPU-native shape: the target network's logp and dist inputs for the whole
train batch are computed ONCE per update in a single jitted forward and
attached to the batch as plain [N, T(,A)] columns — so the per-minibatch
grad step stays the same single XLA program as IMPALA's (no recompile
when the target net refreshes, no target params captured as constants),
and minibatch slicing/shuffling needs no special cases. The adaptive KL
coefficient is likewise shipped as a batch column, keeping the jitted
loss closed over nothing mutable.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ray_tpu.rllib.algorithms.algorithm_config import AlgorithmConfig
from ray_tpu.rllib.algorithms.impala.impala import IMPALA, IMPALAConfig, IMPALALearner, vtrace


class APPOConfig(IMPALAConfig):
    def __init__(self):
        super().__init__()
        self.lr = 5e-4
        self.clip_param = 0.4
        self.use_kl_loss = False
        self.kl_coeff = 1.0
        self.kl_target = 0.01
        # target network refresh cadence, in learner update() calls; tau=1
        # is a hard copy (the reference default), tau<1 polyak-mixes
        self.target_network_update_freq = 1
        self.tau = 1.0
        self.num_epochs = 1

    @property
    def algo_class(self):
        return APPO


class APPOLearner(IMPALALearner):
    """IMPALA learner + target network + PPO surrogate.

    The target ("old") policy plays two roles (appo_torch_learner):
    1. V-trace IS ratios use target-vs-behavior logp, so advantages are
       estimates for the target policy, not the (moving) current one.
    2. The PPO ratio current/target is decomposed as
       clip(behavior/target, 0, 2) * (current/behavior) so each factor is
       computable from stored columns without re-running the target net
       inside the minibatch loop.
    """

    def build(self, seed: int = 0):
        super().build(seed)
        self.target_params = jax.tree.map(jnp.array, self.params)
        self._updates = 0
        self._kl_coeff = float(self.config.kl_coeff)
        module = self.module
        dist = self.module.action_dist_cls

        def target_forward(target_params, obs, actions):
            N, Tp1 = obs.shape[0], obs.shape[1]
            out = module.forward(target_params, obs.reshape((N * Tp1,) + obs.shape[2:]))
            inputs = out["action_dist_inputs"].reshape(N, Tp1, -1)[:, :-1]
            return dist.logp(inputs, actions), inputs

        self._target_forward = jax.jit(target_forward)

    def update(self, batch: dict, **kw) -> dict:
        old_logp, old_inputs = self._target_forward(
            self.target_params, jnp.asarray(batch["obs"]), jnp.asarray(batch["actions"])
        )
        batch = dict(batch)
        batch["old_logp"] = np.asarray(old_logp)
        batch["old_inputs"] = np.asarray(old_inputs)
        N = len(batch["old_logp"])
        batch["kl_coeff"] = np.full((N,), self._kl_coeff, np.float32)
        metrics = super().update(batch, **kw)

        self._updates += 1
        cfg = self.config
        if self._updates % cfg.target_network_update_freq == 0:
            tau = cfg.tau
            self.target_params = jax.tree.map(
                lambda t, p: p if tau >= 1.0 else (1.0 - tau) * t + tau * p,
                self.target_params,
                self.params,
            )
        if cfg.use_kl_loss and "mean_kl" in metrics:
            # the reference's 2x/0.5x adaptive rule (appo learner
            # _update_module_kl_coeff)
            if metrics["mean_kl"] > 2.0 * cfg.kl_target:
                self._kl_coeff *= 1.5
            elif metrics["mean_kl"] < 0.5 * cfg.kl_target:
                self._kl_coeff *= 0.5
            metrics["kl_coeff"] = self._kl_coeff
        return metrics

    def compute_losses(self, params, batch):
        cfg = self.config
        N, T = batch["rewards"].shape
        obs_flat = batch["obs"].reshape((N * (T + 1),) + batch["obs"].shape[2:])
        out = self.module.forward(params, obs_flat)
        dist = self.module.action_dist_cls
        inputs = out["action_dist_inputs"].reshape(N, T + 1, -1)[:, :-1]
        values_all = out["vf"].reshape(N, T + 1)
        values, bootstrap = values_all[:, :-1], values_all[:, -1]

        curr_logp = dist.logp(inputs, batch["actions"])
        behavior_logp = batch["logp"]
        old_logp = batch["old_logp"]
        mask = batch["mask"]

        # advantages for the TARGET policy: V-trace with target-vs-behavior
        # importance weights
        vs, pg_adv = vtrace(
            behavior_logp,
            old_logp,
            batch["rewards"],
            values,
            bootstrap,
            mask,
            batch["nonterminal"],
            cfg.gamma,
            cfg.rho_clip,
            cfg.c_clip,
        )

        # current/target ratio via the behavior anchor (IMPACT):
        # clip(pi_b/pi_old, 0, 2) * pi_cur/pi_b
        is_ratio = jnp.clip(jnp.exp(behavior_logp - old_logp), 0.0, 2.0)
        ratio = is_ratio * jnp.exp(curr_logp - behavior_logp)
        denom = jnp.maximum(jnp.sum(mask), 1.0)
        surrogate = jnp.minimum(
            pg_adv * ratio,
            pg_adv * jnp.clip(ratio, 1.0 - cfg.clip_param, 1.0 + cfg.clip_param),
        )
        policy_loss = -jnp.sum(surrogate * mask) / denom
        vf_loss = 0.5 * jnp.sum(((vs - values) ** 2) * mask) / denom
        entropy = jnp.sum(dist.entropy(inputs) * mask) / denom
        mean_kl = jnp.sum(dist.kl(batch["old_inputs"], inputs) * mask) / denom

        total = policy_loss + cfg.vf_loss_coeff * vf_loss - cfg.entropy_coeff * entropy
        if cfg.use_kl_loss:
            total = total + batch["kl_coeff"][0] * mean_kl
        return total, {
            "total_loss": total,
            "policy_loss": policy_loss,
            "vf_loss": vf_loss,
            "entropy": entropy,
            "mean_kl": mean_kl,
        }

    def get_state(self) -> dict:
        state = super().get_state()
        state["target_params"] = jax.tree.map(np.asarray, self.target_params)
        state["kl_coeff"] = self._kl_coeff
        state["num_updates"] = self._updates
        return state

    def set_state(self, state: dict):
        super().set_state(state)
        if "target_params" in state:
            self.target_params = jax.tree.map(jnp.asarray, state["target_params"])
        self._kl_coeff = float(state.get("kl_coeff", self._kl_coeff))
        # restore the refresh cadence too, or the first post-restore target
        # refresh would drift up to 2*freq-1 updates stale
        self._updates = int(state.get("num_updates", self._updates))


class APPO(IMPALA):
    learner_cls = APPOLearner

    def setup(self):
        cfg = self.config
        if cfg.use_kl_loss and cfg.num_learners > 0:
            # each learner actor would adapt kl_coeff from its own shard's
            # mean_kl, so the coefficients drift apart while grads are
            # still allreduced — an ill-defined mixed objective. Gate it
            # until coefficients sync through the collective.
            raise NotImplementedError(
                "use_kl_loss with remote learners is not supported: the adaptive "
                "kl_coeff is per-learner state; run num_learners=0 or disable use_kl_loss"
            )
        super().setup()
