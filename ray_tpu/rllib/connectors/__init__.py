from ray_tpu.rllib.connectors.connector import (
    CastToFloat32,
    ClipActions,
    ConnectorPipeline,
    ConnectorV2,
    FlattenObs,
    NormalizeObs,
    RescaleActions,
)

__all__ = [
    "ConnectorV2",
    "ConnectorPipeline",
    "FlattenObs",
    "CastToFloat32",
    "NormalizeObs",
    "ClipActions",
    "RescaleActions",
]
