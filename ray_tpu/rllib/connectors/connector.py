"""Connector pipelines: composable env<->module transforms.

Reference parity: rllib/connectors/connector_v2.py:1 (ConnectorV2 +
ConnectorPipelineV2) — the abstraction that moves obs/action
preprocessing OUT of hardcoded runner logic. TPU-native shape: a
connector is a picklable callable over numpy batches on the CPU rollout
path (the jitted module forward stays pure); env-to-module pipelines run
on the stacked obs batch right before the forward pass, module-to-env
pipelines on the sampled action batch right before env.step.

Stateful connectors (NormalizeObs) carry their state on the instance;
it ships with the runner (each remote runner keeps its own running
statistics, like the reference's per-worker connector states).
"""

from __future__ import annotations

import numpy as np


class ConnectorV2:
    """Base: __call__(batch, **ctx) -> batch. ``ctx`` carries optional
    keywords (module, spaces) that concrete connectors may use."""

    def __call__(self, batch, **ctx):
        raise NotImplementedError

    def get_state(self) -> dict:
        return {}

    def set_state(self, state: dict):
        pass


class ConnectorPipeline(ConnectorV2):
    """Ordered composition (reference: ConnectorPipelineV2 with
    insert/append/prepend editing)."""

    def __init__(self, *connectors: ConnectorV2):
        self.connectors = list(connectors)

    def __call__(self, batch, **ctx):
        for c in self.connectors:
            batch = c(batch, **ctx)
        return batch

    def append(self, connector: ConnectorV2) -> "ConnectorPipeline":
        self.connectors.append(connector)
        return self

    def prepend(self, connector: ConnectorV2) -> "ConnectorPipeline":
        self.connectors.insert(0, connector)
        return self

    def remove(self, connector_cls: type) -> bool:
        for i, c in enumerate(self.connectors):
            if isinstance(c, connector_cls):
                del self.connectors[i]
                return True
        return False

    def get_state(self) -> dict:
        return {i: c.get_state() for i, c in enumerate(self.connectors)}

    def set_state(self, state: dict):
        for i, c in enumerate(self.connectors):
            if i in state:
                c.set_state(state[i])


# ---------------------------------------------------------------- env->module


class FlattenObs(ConnectorV2):
    """[B, *obs_shape] -> [B, prod(obs_shape)]."""

    def __call__(self, batch, **ctx):
        batch = np.asarray(batch)
        return batch.reshape(batch.shape[0], -1)


class CastToFloat32(ConnectorV2):
    def __call__(self, batch, **ctx):
        return np.asarray(batch, dtype=np.float32)


class NormalizeObs(ConnectorV2):
    """Running mean/std normalization (reference: MeanStdFilter connector).
    Welford-updated on every batch seen during exploration."""

    def __init__(self, clip: float = 10.0, update: bool = True):
        self.clip = clip
        self.update = update
        self.count = 0.0
        self.mean = None
        self.m2 = None

    def __call__(self, batch, **ctx):
        x = np.asarray(batch, dtype=np.float64)
        flat = x.reshape(x.shape[0], -1)
        if self.mean is None:
            self.mean = np.zeros(flat.shape[1])
            self.m2 = np.ones(flat.shape[1])
        if self.update:
            for row in flat:
                self.count += 1.0
                delta = row - self.mean
                self.mean += delta / self.count
                self.m2 += delta * (row - self.mean)
        std = np.sqrt(self.m2 / max(self.count, 1.0)) + 1e-8
        out = np.clip((flat - self.mean) / std, -self.clip, self.clip)
        return out.reshape(x.shape).astype(np.float32)

    def get_state(self) -> dict:
        return {"count": self.count, "mean": self.mean, "m2": self.m2}

    def set_state(self, state: dict):
        self.count = state["count"]
        self.mean = state["mean"]
        self.m2 = state["m2"]


# ---------------------------------------------------------------- module->env


class ClipActions(ConnectorV2):
    """Clip continuous actions into the env's bounds (reference:
    clip_actions connector piece)."""

    def __init__(self, low=None, high=None):
        self.low = low
        self.high = high

    def __call__(self, batch, **ctx):
        low, high = self.low, self.high
        if low is None and "action_space" in ctx:
            low, high = ctx["action_space"].low, ctx["action_space"].high
        return np.clip(np.asarray(batch), low, high)


class RescaleActions(ConnectorV2):
    """Map module actions in [-1, 1] to the env's [low, high]
    (reference: unsquash_actions)."""

    def __init__(self, low, high):
        self.low = np.asarray(low, dtype=np.float32)
        self.high = np.asarray(high, dtype=np.float32)

    def __call__(self, batch, **ctx):
        a = np.asarray(batch, dtype=np.float32)
        return self.low + (np.clip(a, -1.0, 1.0) + 1.0) * 0.5 * (self.high - self.low)
