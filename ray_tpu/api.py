"""Public API: init/shutdown, @remote, get/put/wait, actors.

Reference parity: python/ray/_private/worker.py (ray.init :1432, get/put/wait
wrappers), python/ray/remote_function.py (RemoteFunction._remote :314),
python/ray/actor.py (ActorClass :1189, ActorClass._remote :1499).
"""

from __future__ import annotations

import functools
import hashlib
import inspect
import threading

from ray_tpu.core import context
from ray_tpu.core.ids import ActorID
from ray_tpu.core.object_ref import ObjectRef, ObjectRefGenerator
from ray_tpu.core.payloads import encode_value
from ray_tpu.core.serialization import serialize
from ray_tpu.core.task_spec import ArgSpec
from ray_tpu.exceptions import GetTimeoutError

_init_lock = threading.Lock()


# ----------------------------------------------------------------------
# init / shutdown
# ----------------------------------------------------------------------
def init(
    address: str | None = None,
    *,
    num_cpus: int | None = None,
    num_tpus: int | None = None,
    resources: dict | None = None,
    local_mode: bool = False,
    namespace: str = "default",
    ignore_reinit_error: bool = False,
    labels: dict | None = None,
    log_to_driver: bool = True,
    _system_config: dict | None = None,
    **kwargs,
):
    from ray_tpu.core.runtime import Runtime

    with _init_lock:
        if context.is_initialized():
            if ignore_reinit_error:
                return context.get_client()
            raise RuntimeError("ray_tpu.init() called twice; pass ignore_reinit_error=True to allow")
        # driver attach: explicit address, or RT_HEAD_ADDRESS exported by
        # the job manager so submitted entrypoints join the RUNNING
        # cluster (reference: ray.init(address=...) / RAY_ADDRESS)
        import os as _os

        wants_own_runtime = local_mode or num_cpus is not None or num_tpus is not None or resources
        if address is not None and wants_own_runtime:
            # the reference errors on address + resource-arg conflicts too:
            # an attached driver cannot size or localize the cluster
            raise ValueError(
                "init(address=...) attaches to an existing cluster; "
                "num_cpus/num_tpus/resources/local_mode cannot apply there"
            )
        if address is None and not wants_own_runtime:
            # env-derived attach (jobs) only when the caller didn't ask for
            # a self-contained runtime — explicit sizing args win over env
            address = _os.environ.get("RT_HEAD_ADDRESS") or None
        if address is not None:
            from ray_tpu.core.driver_client import connect_driver

            client = connect_driver(address)
            if namespace != "default":
                client.namespace = namespace
            context.set_client(client)
            return client
        res = dict(resources or {})
        if num_cpus is not None:
            res["CPU"] = float(num_cpus)
        if num_tpus is not None:
            res["TPU"] = float(num_tpus)
        rt = Runtime(
            resources=res or None,
            local_mode=local_mode,
            namespace=namespace,
            system_config=_system_config,
            labels=labels,
        )
        context.set_client(rt)
        return rt


def shutdown():
    client = context.maybe_client()
    if client is not None and hasattr(client, "shutdown"):
        # head runtimes only: an attached driver's sparse view must not
        # clobber the head's usage_stats.json (same session dir)
        if not getattr(client, "is_driver_attach", False):
            from ray_tpu.util import usage

            try:
                usage.write_usage_stats(client)  # no-op unless RT_USAGE_STATS_ENABLED=1
            except Exception:
                pass
        client.shutdown()
    context.set_client(None)


def is_initialized() -> bool:
    return context.is_initialized()


def _auto_init():
    if not context.is_initialized():
        init()
    return context.get_client()


# ----------------------------------------------------------------------
# object API
# ----------------------------------------------------------------------
def put(value) -> ObjectRef:
    client = _auto_init()
    if isinstance(value, ObjectRef):
        raise TypeError("put() does not accept ObjectRefs")
    return client.put_object(value)


def get(refs, *, timeout: float | None = None):
    import time as _time

    client = _auto_init()
    if isinstance(refs, ObjectRef):
        return client.get_object(refs.id, timeout=timeout)
    if isinstance(refs, (list, tuple)):
        # timeout is an overall deadline across the whole batch
        deadline = None if timeout is None else _time.monotonic() + timeout
        out = []
        for r in refs:
            if not isinstance(r, ObjectRef):
                raise TypeError(f"get() expects ObjectRefs, got {type(r)}")
            remaining = None if deadline is None else max(0.0, deadline - _time.monotonic())
            out.append(client.get_object(r.id, timeout=remaining))
        return out
    raise TypeError(f"get() expects an ObjectRef or list, got {type(refs)}")


def wait(refs, *, num_returns: int = 1, timeout: float | None = None, fetch_local: bool = True):
    client = _auto_init()
    refs = list(refs)
    by_id = {r.id: r for r in refs}
    ready_ids, rest_ids = client.wait_ready([r.id for r in refs], num_returns=num_returns, timeout=timeout)
    return [by_id[i] for i in ready_ids], [by_id[i] for i in rest_ids]


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = True):
    from ray_tpu.core import direct as _direct

    client = _auto_init()
    if _direct.cancel_owned(client, ref.id, force=force):
        return  # direct-plane call: cancel delivered to its worker
    client.cancel_task(ref.id, force=force)


def internal_free(refs):
    _auto_init().free_objects([r.id for r in refs])


# ----------------------------------------------------------------------
# task/actor options
# ----------------------------------------------------------------------
_VALID_OPTIONS = {
    "num_cpus",
    "num_gpus",
    "num_tpus",
    "resources",
    "memory",
    "num_returns",
    "max_retries",
    "retry_exceptions",
    "max_restarts",
    "max_task_retries",
    "max_concurrency",
    "max_pending_calls",
    "name",
    "namespace",
    "lifetime",
    "scheduling_strategy",
    "placement_group",
    "placement_group_bundle_index",
    "placement_group_capture_child_tasks",
    "runtime_env",
    "label_selector",
    "concurrency_groups",
    "accelerator_type",
}


def _with_trace(options: dict, name: str) -> dict:
    """Attach the caller's trace context to an outgoing submission and
    record the client-side span (reference: tracing_helper.py wrapping
    every .remote); a no-op boolean check when tracing is off."""
    from ray_tpu.util import tracing

    if not tracing.enabled():
        return options
    import time as _time
    import uuid as _uuid

    ctx = tracing.child_context()
    span_id = _uuid.uuid4().hex[:16]
    now = _time.time_ns()
    tracing.record_span(f"submit::{name}", "client", ctx[0], span_id, ctx[1], now, now, {})
    return {**(options or {}), "_trace_ctx": (ctx[0], span_id)}


def _check_options(opts: dict):
    unknown = set(opts) - _VALID_OPTIONS
    if unknown:
        raise ValueError(f"unknown option(s): {sorted(unknown)}")


def _encode_args(args, kwargs):
    """Encode call arguments into ArgSpecs. Owned refs (direct call plane)
    are tagged with their owner address so the executing worker pulls them
    straight from the owner; `pins` are live ObjectRefs held until the call
    completes (the caller-side analogue of the head's pin_spec_args)."""
    from ray_tpu.core import direct as _direct

    pins = []

    def one(a):
        if isinstance(a, ObjectRef):
            pins.append(a)
            k = a.id.binary()
            st = _direct.state()
            if st is not None and st.owned.owns(k):
                owner = st.self_owner
            else:
                owner = _direct.get_hint(k)
            return ArgSpec(ref=a.id, owner=owner)
        payload = encode_value(a)
        for c in payload.contained or []:
            pins.append(ObjectRef(c))
        return ArgSpec(payload=payload)

    arg_specs = [one(a) for a in args]
    kw_specs = {k: one(v) for k, v in (kwargs or {}).items()}
    return arg_specs, kw_specs, pins


def _num_returns(opts, default=1):
    nr = opts.get("num_returns", default)
    if nr in ("streaming", "dynamic"):
        return 1, True
    return int(nr), False


# ----------------------------------------------------------------------
# remote functions
# ----------------------------------------------------------------------
class RemoteFunction:
    def __init__(self, fn, options: dict | None = None):
        if inspect.iscoroutinefunction(fn):
            raise TypeError("async functions can only be actor methods")
        self._fn = fn
        self._options = dict(options or {})
        self._blob = None
        self._func_id = None
        functools.update_wrapper(self, fn)

    def _ensure_registered(self, client):
        if self._func_id is None:
            from ray_tpu.core.serialization import Serialized

            s = serialize(self._fn)
            bufs = [bytes(b) for b in s.buffers]
            self._func_id = hashlib.sha1(bytes(s.header) + b"".join(bufs)).hexdigest()
            self._blob = Serialized(header=bytes(s.header), buffers=bufs)
        if not client.has_function(self._func_id):
            return self._blob
        return None

    def options(self, **opts) -> "RemoteFunction":
        _check_options(opts)
        merged = {**self._options, **opts}
        rf = RemoteFunction(self._fn, merged)
        rf._blob = self._blob
        rf._func_id = self._func_id
        return rf

    def remote(self, *args, **kwargs):
        from ray_tpu.core import direct as _direct

        client = _auto_init()
        blob = self._ensure_registered(client)
        name = getattr(self._fn, "__name__", "task")
        num_returns, streaming = _num_returns(self._options)
        opts = _with_trace(self._options, name)
        if not streaming and _direct.state() is not None and _direct.raw_eligible(args, kwargs):
            # direct plane fast path: args ride the call frame as plain
            # values — ONE pickle for the whole submission (core/direct.py)
            refs = _direct.try_task_call(client, name, self._func_id, self._blob, None, None, opts, raw=(args, kwargs))
            if refs is not None:
                return refs[0] if num_returns == 1 else refs
        arg_specs, kw_specs, pins = _encode_args(args, kwargs)
        if not streaming:
            # direct plane: stream the task onto a leased worker, head out
            # of the loop (returns None -> head path)
            refs = _direct.try_task_call(client, name, self._func_id, self._blob, arg_specs, kw_specs, opts, pins=pins)
            if refs is not None:
                return refs[0] if num_returns == 1 else refs
        _direct.promote_argspecs(client, arg_specs, kw_specs)
        ids = client.submit_task(
            name=name,
            func_id=self._func_id,
            args=arg_specs,
            kwargs=kw_specs,
            num_returns=num_returns,
            streaming=streaming,
            func_blob=blob,
            options=opts,
        )
        if hasattr(client, "mark_function_sent"):
            client.mark_function_sent(self._func_id)
        if streaming:
            return ObjectRefGenerator(ids[0])
        refs = [ObjectRef(i) for i in ids]
        return refs[0] if num_returns == 1 else refs

    def bind(self, *args, **kwargs):
        from ray_tpu.dag import FunctionNode

        return FunctionNode(self, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(f"remote function {self.__name__}() cannot be called directly; use .remote()")


# ----------------------------------------------------------------------
# actors
# ----------------------------------------------------------------------
class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str, options: dict | None = None):
        self._handle = handle
        self._name = name
        self._options = dict(options or {})

    def options(self, **opts) -> "ActorMethod":
        return ActorMethod(self._handle, self._name, {**self._options, **opts})

    def remote(self, *args, **kwargs):
        from ray_tpu.core import direct as _direct

        client = _auto_init()
        num_returns, streaming = _num_returns(self._options)
        opts = _with_trace(self._options, self._name)
        if not streaming and _direct.state() is not None and _direct.raw_eligible(args, kwargs):
            # direct plane fast path: args ride the call frame directly
            refs = _direct.try_actor_call(client, self._handle._actor_id, self._name, None, None, opts, raw=(args, kwargs))
            if refs is not None:
                return refs[0] if num_returns == 1 else refs
        arg_specs, kw_specs, pins = _encode_args(args, kwargs)
        if not streaming:
            # direct plane: straight to the actor's worker (core/direct.py)
            refs = _direct.try_actor_call(client, self._handle._actor_id, self._name, arg_specs, kw_specs, opts, pins=pins)
            if refs is not None:
                return refs[0] if num_returns == 1 else refs
        # head path: owned args move to the head store first, and the
        # direct lane drains so per-caller ordering holds across lanes
        _direct.promote_argspecs(client, arg_specs, kw_specs)
        _direct.head_lane_submit(self._handle._actor_id)
        ids = client.submit_actor_task(
            actor_id=self._handle._actor_id,
            method_name=self._name,
            args=arg_specs,
            kwargs=kw_specs,
            num_returns=num_returns,
            streaming=streaming,
            options=opts,
        )
        if streaming:
            return ObjectRefGenerator(ids[0])
        refs = [ObjectRef(i) for i in ids]
        return refs[0] if num_returns == 1 else refs

    def bind(self, *args, **kwargs):
        from ray_tpu.dag import ActorMethodNode

        return ActorMethodNode(self._handle, self._name, args, kwargs)


class ActorHandle:
    def __init__(self, actor_id: ActorID, method_options: dict | None = None):
        self._actor_id = actor_id
        self._method_options = method_options or {}

    def __getattr__(self, name):
        # __rt_*__ names are runtime-builtin actor methods (collective init,
        # device-object export) served by worker_main for every actor
        if name.startswith("_") and not (name.startswith("__rt_") and name.endswith("__")):
            raise AttributeError(name)
        return ActorMethod(self, name, self._method_options.get(name))

    def __ray_ready__(self):
        client = _auto_init()
        if hasattr(client, "actor_ready_ref"):
            return client.actor_ready_ref(self._actor_id)
        from ray_tpu.core.runtime import _actor_ready_oid

        return ObjectRef(_actor_ready_oid(self._actor_id))

    def __repr__(self):
        return f"ActorHandle({self._actor_id.hex()[:16]})"

    def __eq__(self, other):
        return isinstance(other, ActorHandle) and self._actor_id == other._actor_id

    def __hash__(self):
        return hash(self._actor_id)

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._method_options))


class ActorClass:
    def __init__(self, cls, options: dict | None = None):
        self._cls = cls
        self._options = dict(options or {})
        self._blob = None
        self._class_id = None
        self.__name__ = cls.__name__

    def options(self, **opts) -> "ActorClass":
        _check_options(opts)
        ac = ActorClass(self._cls, {**self._options, **opts})
        ac._blob = self._blob
        ac._class_id = self._class_id
        return ac

    def _ensure_registered(self, client):
        if self._class_id is None:
            from ray_tpu.core.serialization import Serialized

            s = serialize(self._cls)
            bufs = [bytes(b) for b in s.buffers]
            self._class_id = hashlib.sha1(bytes(s.header) + b"".join(bufs)).hexdigest()
            self._blob = Serialized(header=bytes(s.header), buffers=bufs)
        if not client.has_function(self._class_id):
            return self._blob
        return None

    def remote(self, *args, **kwargs) -> ActorHandle:
        from ray_tpu.core import direct as _direct

        client = _auto_init()
        blob = self._ensure_registered(client)
        arg_specs, kw_specs, _pins = _encode_args(args, kwargs)
        _direct.promote_argspecs(client, arg_specs, kw_specs)  # creation is head-path
        opts = dict(self._options)
        if any(inspect.iscoroutinefunction(m) for _, m in inspect.getmembers(self._cls, inspect.isfunction)):
            opts.setdefault("max_concurrency", 8)
        method_options = {}
        for name, m in inspect.getmembers(self._cls, inspect.isfunction):
            mo = getattr(m, "__ray_tpu_method_options__", None)
            if mo:
                method_options[name] = mo
        info = client.create_actor(
            name_desc=self._cls.__name__,
            func_id=self._class_id,
            args=arg_specs,
            kwargs=kw_specs,
            func_blob=blob,
            options=opts,
        )
        if hasattr(client, "mark_function_sent"):
            client.mark_function_sent(self._class_id)
        return ActorHandle(info["actor_id"], method_options)

    def bind(self, *args, **kwargs):
        from ray_tpu.dag import ClassNode

        return ClassNode(self, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(f"actor class {self.__name__} cannot be instantiated directly; use .remote()")


def method(**opts):
    """Per-method options decorator (reference: ray.method)."""

    def deco(fn):
        fn.__ray_tpu_method_options__ = opts
        return fn

    return deco


# ----------------------------------------------------------------------
# the @remote decorator
# ----------------------------------------------------------------------
def remote(*args, **kwargs):
    if len(args) == 1 and not kwargs and (inspect.isfunction(args[0]) or inspect.isclass(args[0])):
        target = args[0]
        if inspect.isclass(target):
            return ActorClass(target)
        return RemoteFunction(target)
    _check_options(kwargs)
    opts = kwargs

    def deco(target):
        if inspect.isclass(target):
            return ActorClass(target, opts)
        return RemoteFunction(target, opts)

    return deco


# ----------------------------------------------------------------------
# actor management
# ----------------------------------------------------------------------
def get_actor(name: str, namespace: str = "default") -> ActorHandle:
    client = _auto_init()
    info = client.get_actor_handle_info(name, namespace)
    if info is None:
        raise ValueError(f"actor {name!r} not found in namespace {namespace!r}")
    return ActorHandle(info["actor_id"])


def kill(handle: ActorHandle, *, no_restart: bool = True):
    _auto_init().kill_actor(handle._actor_id, no_restart=no_restart)


# ----------------------------------------------------------------------
# cluster info
# ----------------------------------------------------------------------
def nodes() -> list[dict]:
    return _auto_init().cluster_info("nodes")


def cluster_resources() -> dict:
    return _auto_init().cluster_info("cluster_resources")


def available_resources() -> dict:
    return _auto_init().cluster_info("available_resources")


def get_runtime_context():
    from ray_tpu.core.context import get_runtime_context as _grc

    _auto_init()
    return _grc()
