"""DataIterator: rebatching consumption of a block-ref stream.

Reference parity: python/ray/data/iterator.py (iter_batches /
iter_torch_batches / to_tf) + _internal/block_batching. The train
integration hands each worker a DataIterator (get_dataset_shard).
"""

from __future__ import annotations

import collections
from typing import Iterator

import numpy as np

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor


def _rebatch(blocks: Iterator[Block], batch_size: int | None, batch_format: str, drop_last: bool):
    if batch_size is None:
        for b in blocks:
            if b.num_rows:
                yield BlockAccessor(b).to_batch(batch_format)
        return
    buf: list[Block] = []
    buffered = 0
    for b in blocks:
        if not b.num_rows:
            continue
        buf.append(b)
        buffered += b.num_rows
        while buffered >= batch_size:
            merged = BlockAccessor.concat(buf)
            out = BlockAccessor(merged).slice(0, batch_size)
            rest = BlockAccessor(merged).slice(batch_size, merged.num_rows)
            yield BlockAccessor(out).to_batch(batch_format)
            buf = [rest] if rest.num_rows else []
            buffered = rest.num_rows
    if buffered and not drop_last:
        yield BlockAccessor(BlockAccessor.concat(buf)).to_batch(batch_format)


class DataIterator:
    """Iterates a (re-runnable) stream of block refs."""

    def __init__(self, ref_stream_factory):
        self._factory = ref_stream_factory

    def _blocks(self, prefetch: int) -> Iterator[Block]:
        refs = self._factory()
        window: collections.deque = collections.deque()
        for ref in refs:
            window.append(ref)
            if len(window) > prefetch:
                yield ray_tpu.get(window.popleft())
        while window:
            yield ray_tpu.get(window.popleft())

    def iter_batches(
        self,
        *,
        batch_size: int | None = 256,
        batch_format: str = "numpy",
        drop_last: bool = False,
        prefetch_batches: int = 2,
        local_shuffle_buffer_size: int | None = None,
        local_shuffle_seed: int | None = None,
    ):
        blocks = self._blocks(prefetch=max(prefetch_batches, 1))
        if local_shuffle_buffer_size:
            blocks = _shuffle_blocks(blocks, local_shuffle_buffer_size, local_shuffle_seed)
        yield from _rebatch(blocks, batch_size, batch_format, drop_last)

    def iter_rows(self):
        for b in self._blocks(prefetch=2):
            yield from BlockAccessor(b).iter_rows()

    def iter_torch_batches(self, *, batch_size: int | None = 256, drop_last: bool = False, **kw):
        import torch

        for batch in self.iter_batches(batch_size=batch_size, batch_format="numpy", drop_last=drop_last, **kw):
            yield {k: torch.as_tensor(v) for k, v in batch.items()}

    def materialize(self):
        from ray_tpu.data.dataset import MaterializedDataset

        return MaterializedDataset(list(self._factory()))


def _shuffle_blocks(blocks: Iterator[Block], buffer_rows: int, seed):
    rng = np.random.default_rng(seed)
    buf: list[Block] = []
    size = 0
    for b in blocks:
        buf.append(b)
        size += b.num_rows
        if size >= buffer_rows:
            merged = BlockAccessor.concat(buf)
            yield BlockAccessor(merged).take_indices(rng.permutation(merged.num_rows))
            buf, size = [], 0
    if buf:
        merged = BlockAccessor.concat(buf)
        yield BlockAccessor(merged).take_indices(rng.permutation(merged.num_rows))


@ray_tpu.remote(max_concurrency=16)
class SplitCoordinator:
    """Serves one executing stream to n consumers (reference:
    _internal/execution/operators/output_splitter.py + streaming_split
    coordinator actor): each consumer pulls its next block ref; assignment
    is round-robin at pull time, so faster consumers do not starve.

    equal=True re-chunks the stream into fixed-row chunks dealt round-robin
    and splits the tail evenly (dropping up to n-1 remainder rows), so every
    consumer sees exactly the same row count — required for synchronized
    SPMD training loops (reference: output_splitter equal splitting)."""

    EQUAL_CHUNK_ROWS = 256
    # bound on one upstream block materializing (tpulint TPL001): the
    # coordinator is an actor, and an unbounded get on a wedged producer
    # would deadlock every consumer behind it with no error surfacing;
    # generous enough for a slow lineage reconstruction, finite so a hang
    # becomes a GetTimeoutError the consumers actually see
    STREAM_GET_TIMEOUT_S = 600.0

    def __init__(self, dataset, n: int, equal: bool, locality_hints=None):
        self.n = n
        self.equal = equal
        self.queues = [collections.deque() for _ in range(n)]
        self._stream = dataset._ref_stream()
        self._pending = None  # equal mode: pulled-but-not-gotten block ref
        self._exhausted = False
        self._next = 0
        self._carry = None  # equal mode: residual rows awaiting a full chunk
        # locality_hints: node-id hex per consumer (reference:
        # output_splitter.py locality-aware bundle routing) — blocks whose
        # primary copy lives on a consumer's hinted node go to that
        # consumer, under a BALANCE BOUND: locality is a preference, so a
        # split whose hinted node holds every block cannot starve the
        # others (unmatched / over-budget blocks round-robin). Stats back
        # the majority-local assertion in tests.
        if locality_hints is not None:
            if equal:
                raise ValueError("locality_hints are not supported with equal=True (re-chunked rows have no single home node)")
            if len(locality_hints) != n:
                raise ValueError(f"need one locality hint per split: got {len(locality_hints)} for n={n}")
        self._hints = list(locality_hints) if locality_hints else None
        self._assigned = [0] * n
        self.stats = [{"local": 0, "remote": 0} for _ in range(n)]
        import threading

        self._lock = threading.Lock()

    LOCALITY_SKEW_BOUND = 4  # max extra blocks a hinted split may run ahead

    def _route(self, ref) -> int:
        """Pick the consumer for a freshly pulled block ref. One location
        lookup per block (each block is routed exactly once; the
        coordinator actor serializes calls anyway, so the RPC adds no
        extra contention)."""
        if self._hints:
            from ray_tpu.core import context as _ctx

            loc = _ctx.get_client().object_locations([ref.id]).get(ref.id.hex())
            if loc is not None:
                floor = min(self._assigned)
                matches = [
                    i
                    for i, h in enumerate(self._hints)
                    if h == loc and self._assigned[i] - floor < self.LOCALITY_SKEW_BOUND
                ]
                if matches:
                    target = min(matches, key=lambda i: self._assigned[i])
                    self._assigned[target] += 1
                    self.stats[target]["local"] += 1
                    return target
        target = self._next % self.n
        self._next += 1
        self._assigned[target] += 1
        self.stats[target]["remote"] += 1
        return target

    def locality_stats(self):
        return self.stats

    def _pump_equal(self):
        """Pull source blocks until one full round of n chunks is queued, or
        the stream ends (then deal the tail evenly, dropping < n rows)."""
        chunk = self.EQUAL_CHUNK_ROWS
        while not self._exhausted:
            rows = self._carry.num_rows if self._carry is not None else 0
            if rows >= chunk * self.n:
                break
            if self._pending is None:
                try:
                    self._pending = next(self._stream)
                except StopIteration:
                    self._exhausted = True
                    break
            # a timeout leaves the ref parked in _pending: the next call
            # re-gets the SAME block, so a slow producer surfaces as an
            # error without silently dropping its rows from the stream
            block = ray_tpu.get(self._pending, timeout=self.STREAM_GET_TIMEOUT_S)
            self._pending = None
            self._carry = block if self._carry is None else BlockAccessor.concat([self._carry, block])
        buf = self._carry
        if buf is None:
            return
        acc = BlockAccessor(buf)
        if not self._exhausted:
            for i in range(self.n):
                self.queues[i].append(ray_tpu.put(acc.slice(i * chunk, (i + 1) * chunk)))
            self._carry = acc.slice(chunk * self.n, buf.num_rows)
        else:
            per = buf.num_rows // self.n
            if per:
                for i in range(self.n):
                    self.queues[i].append(ray_tpu.put(acc.slice(i * per, (i + 1) * per)))
            self._carry = None

    def next_ref(self, split: int):
        """Returns an ObjectRef or None when the stream is exhausted."""
        with self._lock:
            if self.queues[split]:
                return self.queues[split].popleft()
            if self.equal:
                while not self.queues[split]:
                    had_carry = self._carry is not None
                    self._pump_equal()
                    if self._exhausted and not self.queues[split] and not had_carry:
                        return None
                    if self._exhausted and not self.queues[split]:
                        return None
                return self.queues[split].popleft()
            while not self._exhausted:
                try:
                    ref = next(self._stream)
                except StopIteration:
                    self._exhausted = True
                    break
                target = self._route(ref)
                if target == split:
                    return ref
                self.queues[target].append(ref)
            return self.queues[split].popleft() if self.queues[split] else None


class SplitIterator(DataIterator):
    def __init__(self, coordinator, split: int):
        self._coord = coordinator
        self._split = split
        super().__init__(self._pull_refs)

    def _pull_refs(self):
        while True:
            ref = ray_tpu.get(self._coord.next_ref.remote(self._split))
            if ref is None:
                return
            yield ref
