"""Dataset: the lazy, streaming distributed dataset.

Reference parity: python/ray/data/dataset.py — lazy logical plan, executed
by the streaming executor on iteration/materialize (SURVEY.md §3.7).
Transforms return new Datasets sharing the upstream plan (immutable).
"""

from __future__ import annotations

import builtins
import functools
from typing import Any, Callable, Iterable

import numpy as np

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor
from ray_tpu.data.datasource import Datasource, ReadTask
from ray_tpu.data.executor import AllToAllSpec, LimitSpec, MapSpec, execute_plan
from ray_tpu.data.iterator import DataIterator, SplitCoordinator, SplitIterator


class Dataset:
    def __init__(self, source_tasks: list[ReadTask], ops: tuple = ()):
        self._source_tasks = source_tasks
        self._ops = tuple(ops)

    # ---------------- plan building ----------------
    def _with_op(self, op) -> "Dataset":
        return Dataset(self._source_tasks, self._ops + (op,))

    def map_batches(
        self,
        fn: Callable | type,
        *,
        batch_size: int | None = None,
        batch_format: str = "numpy",
        concurrency: int | None = None,
        num_cpus: float = 1.0,
        fn_args: tuple = (),
        fn_kwargs: dict | None = None,
        fn_constructor_args: tuple = (),
        fn_constructor_kwargs: dict | None = None,
        zero_copy_batch: bool = False,
    ) -> "Dataset":
        return self._with_op(
            MapSpec(
                "map_batches",
                fn,
                fn_args=fn_args,
                fn_kwargs=fn_kwargs or {},
                fn_constructor_args=fn_constructor_args,
                fn_constructor_kwargs=fn_constructor_kwargs or {},
                batch_size=batch_size,
                batch_format=batch_format,
                concurrency=concurrency,
                num_cpus=num_cpus,
                zero_copy_batch=zero_copy_batch,
            )
        )

    def map(self, fn, *, concurrency=None, num_cpus: float = 1.0, fn_args=(), fn_kwargs=None) -> "Dataset":
        return self._with_op(
            MapSpec("map", fn, fn_args=fn_args, fn_kwargs=fn_kwargs or {}, concurrency=concurrency, num_cpus=num_cpus)
        )

    def filter(self, fn, *, concurrency=None, fn_args=(), fn_kwargs=None) -> "Dataset":
        return self._with_op(MapSpec("filter", fn, fn_args=fn_args, fn_kwargs=fn_kwargs or {}, concurrency=concurrency))

    def flat_map(self, fn, *, concurrency=None, fn_args=(), fn_kwargs=None) -> "Dataset":
        return self._with_op(MapSpec("flat_map", fn, fn_args=fn_args, fn_kwargs=fn_kwargs or {}, concurrency=concurrency))

    def add_column(self, name: str, fn) -> "Dataset":
        def add(batch):
            batch[name] = fn(batch)
            return batch

        return self.map_batches(add, batch_format="pandas")

    def drop_columns(self, cols: list[str]) -> "Dataset":
        return self.map_batches(lambda b: {k: v for k, v in b.items() if k not in cols})

    def select_columns(self, cols: list[str]) -> "Dataset":
        return self.map_batches(lambda b: {k: b[k] for k in cols})

    def rename_columns(self, mapping: dict) -> "Dataset":
        return self.map_batches(lambda b: {mapping.get(k, k): v for k, v in b.items()})

    def limit(self, n: int) -> "Dataset":
        return self._with_op(LimitSpec(n))

    def repartition(self, num_blocks: int) -> "Dataset":
        return self._with_op(AllToAllSpec("repartition", {"num_blocks": num_blocks}))

    def random_shuffle(self, *, seed: int | None = None) -> "Dataset":
        return self._with_op(AllToAllSpec("random_shuffle", {"seed": seed}))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        return self._with_op(AllToAllSpec("sort", {"key": key, "descending": descending}))

    def union(self, *others: "Dataset") -> "Dataset":
        """Only unions of plain (un-transformed or materialized) datasets
        keep laziness; otherwise operands materialize."""
        all_tasks = list(self._materialized_tasks())
        for o in others:
            all_tasks += o._materialized_tasks()
        return Dataset(all_tasks)

    def join(self, other: "Dataset", on, how: str = "inner", *, num_partitions: int | None = None) -> "Dataset":
        """Distributed hash-shuffle join (reference: ray.data Dataset.join
        backed by hash shuffling). Both sides are partitioned on the key
        columns with the native row hasher (_native/hashing.cpp — FNV-1a
        over raw Arrow string buffers, splitmix64 for numerics), aligned
        buckets are joined with Arrow's join kernel in parallel tasks.

        how: inner | left | right | outer (plus arrow's full names)."""
        from ray_tpu.data.executor import _hash_partition_block, _join_buckets

        from ray_tpu._native import MAX_PARTITIONS

        on = [on] if isinstance(on, str) else list(on)
        left_refs = list(self._ref_stream())
        right_refs = list(other._ref_stream())
        if not left_refs or not right_refs:
            # an empty side has no schema to join against: inner joins are
            # empty by definition; outer joins cannot synthesize the
            # missing side's columns
            if how == "inner":
                return MaterializedDataset([])
            raise ValueError(
                f"{how} join with an empty-side dataset is unsupported: the "
                "empty side has no schema to pad from"
            )
        P = min(num_partitions or max(len(left_refs), len(right_refs), 2), MAX_PARTITIONS)
        lparts = [_hash_partition_block.options(num_returns=P).remote(r, on, P) for r in left_refs]
        rparts = [_hash_partition_block.options(num_returns=P).remote(r, on, P) for r in right_refs]
        if P == 1:
            lparts = [[p] for p in lparts]
            rparts = [[p] for p in rparts]
        out = [
            _join_buckets.remote(how, on, len(lparts), *[lp[i] for lp in lparts], *[rp[i] for rp in rparts])
            for i in builtins.range(P)
        ]
        return MaterializedDataset(out)

    def zip(self, other: "Dataset") -> "Dataset":
        left = self.materialize()
        right = other.materialize()
        lt = BlockAccessor.concat(ray_tpu.get(left._refs))
        rt = BlockAccessor.concat(ray_tpu.get(right._refs))
        if lt.num_rows != rt.num_rows:
            raise ValueError(f"zip row mismatch: {lt.num_rows} vs {rt.num_rows}")
        merged = lt
        for name in rt.column_names:
            out_name = name if name not in lt.column_names else f"{name}_1"
            merged = merged.append_column(out_name, rt.column(name))
        return from_arrow(merged)

    # ---------------- execution ----------------
    def _ref_stream(self):
        return execute_plan(list(self._source_tasks), list(self._ops))

    def _materialized_tasks(self) -> list[ReadTask]:
        if not self._ops:
            return list(self._source_tasks)
        mat = self.materialize()
        return mat._source_tasks

    def iterator(self) -> DataIterator:
        return DataIterator(self._ref_stream)

    def iter_batches(self, **kw):
        return self.iterator().iter_batches(**kw)

    def iter_rows(self):
        return self.iterator().iter_rows()

    def iter_torch_batches(self, **kw):
        return self.iterator().iter_torch_batches(**kw)

    def materialize(self) -> "MaterializedDataset":
        return MaterializedDataset(list(self._ref_stream()))

    def take(self, n: int = 20) -> list[dict]:
        out = []
        for row in self.iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> list[dict]:
        return list(self.iter_rows())

    def take_batch(self, batch_size: int = 20, batch_format: str = "numpy"):
        for b in self.iter_batches(batch_size=batch_size, batch_format=batch_format):
            return b
        return {}

    def count(self) -> int:
        # submit all count kernels first, then one batched get (keeps the
        # streaming window full instead of serializing on each block)
        refs = [_count_block.remote(r) for r in self._ref_stream()]
        return sum(ray_tpu.get(refs))

    def schema(self):
        for ref in self._ref_stream():
            return ray_tpu.get(ref).schema
        return None

    def columns(self) -> list[str]:
        s = self.schema()
        return list(s.names) if s is not None else []

    # ---------------- aggregations ----------------
    def _agg(self, col: str, kind: str):
        refs = [_agg_block.remote(r, col, kind) for r in self._ref_stream()]
        vals = [v for v in ray_tpu.get(refs) if v is not None]
        if not vals:
            return None
        if kind in ("sum", "count"):
            return sum(vals)
        if kind == "min":
            return min(vals)
        if kind == "max":
            return max(vals)
        if kind == "sum_count":  # single-pass mean support
            return (sum(s for s, _ in vals), sum(c for _, c in vals))
        raise ValueError(kind)

    def sum(self, col: str):
        return self._agg(col, "sum")

    def min(self, col: str):
        return self._agg(col, "min")

    def max(self, col: str):
        return self._agg(col, "max")

    def mean(self, col: str):
        # one pass over the plan (sum+count per block), not two executions
        out = self._agg(col, "sum_count")
        if out is None:
            return None
        s, c = out
        return None if not c else s / c

    def groupby(self, key: str) -> "GroupedData":
        return GroupedData(self, key)

    # ---------------- splits ----------------
    def split(self, n: int, *, equal: bool = False) -> list["MaterializedDataset"]:
        refs = list(self._ref_stream())
        if equal:
            total = BlockAccessor.concat(ray_tpu.get(refs))
            rows = total.num_rows - (total.num_rows % n)
            per = rows // n
            return [
                MaterializedDataset([ray_tpu.put(BlockAccessor(total).slice(i * per, (i + 1) * per))])
                for i in builtins.range(n)
            ]
        outs = [[] for _ in builtins.range(n)]
        for i, r in enumerate(refs):
            outs[i % n].append(r)
        return [MaterializedDataset(o) for o in outs]

    def streaming_split(self, n: int, *, equal: bool = False, locality_hints=None) -> list[DataIterator]:
        """locality_hints: one node-id hex per split — each block routes
        to the split whose hinted node holds its primary copy (reference:
        streaming_split locality_hints -> output_splitter routing)."""
        coord = SplitCoordinator.remote(self, n, equal, locality_hints)
        return [SplitIterator(coord, i) for i in builtins.range(n)]

    def train_test_split(self, test_size: float, *, shuffle: bool = False, seed=None):
        ds = self.random_shuffle(seed=seed) if shuffle else self
        mat = ds.materialize()  # single plan execution; count from the blocks
        merged = BlockAccessor.concat(ray_tpu.get(mat._refs))
        total = merged.num_rows
        k = int(total * (1 - test_size))
        acc = BlockAccessor(merged)
        return (
            MaterializedDataset([ray_tpu.put(acc.slice(0, k))]),
            MaterializedDataset([ray_tpu.put(acc.slice(k, merged.num_rows))]),
        )

    # ---------------- writes ----------------
    def _write(self, path: str, fmt: str):
        import os

        os.makedirs(path, exist_ok=True)
        refs = [
            _write_block.remote(ref, path, i, fmt) for i, ref in enumerate(self._ref_stream())
        ]
        return ray_tpu.get(refs)

    def write_parquet(self, path: str):
        return self._write(path, "parquet")

    def write_csv(self, path: str):
        return self._write(path, "csv")

    def write_json(self, path: str):
        return self._write(path, "json")

    def to_pandas(self):
        return BlockAccessor.concat(ray_tpu.get(list(self._ref_stream()))).to_pandas()

    def to_arrow_refs(self):
        return list(self._ref_stream())

    def __repr__(self):
        ops = " -> ".join(type(o).__name__ for o in self._ops) or "read"
        return f"Dataset({len(self._source_tasks)} source tasks, plan: {ops})"


class MaterializedDataset(Dataset):
    """A dataset whose blocks already exist in the object store."""

    def __init__(self, refs: list):
        self._refs = refs
        super().__init__([ReadTask(None) for _ in refs])

    def _ref_stream(self):
        if self._ops:
            return execute_plan_from_refs(self._refs, list(self._ops))
        return iter(self._refs)

    def _with_op(self, op):
        out = MaterializedDataset(self._refs)
        out._ops = self._ops + (op,)
        return out

    def _materialized_tasks(self):
        if self._ops:
            return self.materialize()._source_tasks
        return [ReadTask(lambda b=b: iter([b]), num_rows=None) for b in ray_tpu.get(self._refs)]

    def num_blocks(self) -> int:
        return len(self._refs)


def execute_plan_from_refs(refs, ops):
    return execute_plan([], ops) if not refs else _execute_from_refs(refs, ops)


def _execute_from_refs(refs, ops):
    from ray_tpu.data import executor as ex

    stream = iter(refs)
    for op in ops:
        if isinstance(op, MapSpec):
            stream = ex._map_stage(stream, op)
        elif isinstance(op, LimitSpec):
            stream = ex._limit_stage(stream, op.n)
        elif isinstance(op, AllToAllSpec):
            stream = ex._all_to_all_stage(stream, op)
    return stream


class GroupedData:
    """Hash-shuffle groupby (reference: data/grouped_data.py + hash_shuffle
    physical op)."""

    def __init__(self, ds: Dataset, key: str):
        self.ds = ds
        self.key = key

    def _grouped_blocks(self):
        sorted_ds = self.ds.sort(self.key)
        return list(sorted_ds._ref_stream())

    def _apply(self, agg_fn_name: str, cols: list[str] | None):
        refs = self._grouped_blocks()
        merged = BlockAccessor.concat(ray_tpu.get(refs))
        df = merged.to_pandas()
        g = df.groupby(self.key, sort=True)
        if agg_fn_name == "count":
            out = g.size().reset_index(name="count()")
        else:
            cols = cols or [c for c in df.columns if c != self.key]
            out = getattr(g[cols], agg_fn_name)().reset_index()
            out.columns = [self.key] + [f"{agg_fn_name}({c})" for c in cols]
        return from_pandas(out)

    def count(self):
        return self._apply("count", None)

    def sum(self, *cols):
        return self._apply("sum", list(cols) or None)

    def mean(self, *cols):
        return self._apply("mean", list(cols) or None)

    def min(self, *cols):
        return self._apply("min", list(cols) or None)

    def max(self, *cols):
        return self._apply("max", list(cols) or None)

    def map_groups(self, fn, *, batch_format: str = "pandas"):
        refs = self._grouped_blocks()
        merged = BlockAccessor.concat(ray_tpu.get(refs))
        df = merged.to_pandas()
        outs = []
        for _, group in df.groupby(self.key, sort=True):
            res = fn(group if batch_format == "pandas" else BlockAccessor.batch_to_block(group))
            outs.append(BlockAccessor.batch_to_block(res))
        return MaterializedDataset([ray_tpu.put(b) for b in outs])


# ----------------------------------------------------------------------
# remote kernels for terminal ops
# ----------------------------------------------------------------------
@ray_tpu.remote
def _count_block(block: Block) -> int:
    return block.num_rows


@ray_tpu.remote
def _agg_block(block: Block, col: str, kind: str):
    acc = BlockAccessor(block)
    if block.num_rows == 0:
        return None
    vals = acc.to_numpy([col])[col]
    if kind == "sum":
        return vals.sum()
    if kind == "min":
        return vals.min()
    if kind == "max":
        return vals.max()
    if kind == "count":
        return len(vals)
    if kind == "sum_count":
        return (vals.sum(), len(vals))


@ray_tpu.remote
def _write_block(block: Block, path: str, idx: int, fmt: str) -> str:
    import os

    f = os.path.join(path, f"part-{idx:05d}.{fmt}")
    if fmt == "parquet":
        import pyarrow.parquet as pq

        pq.write_table(block, f)
    elif fmt == "csv":
        import pyarrow.csv as pacsv

        pacsv.write_csv(block, f)
    elif fmt == "json":
        block.to_pandas().to_json(f, orient="records", lines=True)
    return f


# ----------------------------------------------------------------------
# read API (module-level; re-exported by ray_tpu.data.__init__)
# ----------------------------------------------------------------------
def read_datasource(ds: Datasource, *, parallelism: int = -1) -> Dataset:
    if parallelism <= 0:
        parallelism = 8
    return Dataset(ds.get_read_tasks(parallelism))


def range(n: int, *, parallelism: int = -1) -> Dataset:  # noqa: A001
    from ray_tpu.data.datasource import RangeDatasource

    return read_datasource(RangeDatasource(n), parallelism=parallelism)


def from_items(items: list, *, parallelism: int = -1) -> Dataset:
    from ray_tpu.data.datasource import ItemsDatasource

    return read_datasource(ItemsDatasource(items), parallelism=parallelism)


def from_numpy(arr, column: str = "data") -> Dataset:
    from ray_tpu.data.datasource import BlocksDatasource

    return read_datasource(BlocksDatasource([{column: np.asarray(arr)}]), parallelism=1)


def from_pandas(df) -> Dataset:
    from ray_tpu.data.datasource import BlocksDatasource

    return read_datasource(BlocksDatasource([df]), parallelism=1)


def from_arrow(table) -> Dataset:
    from ray_tpu.data.datasource import BlocksDatasource

    return read_datasource(BlocksDatasource([table]), parallelism=1)


def _file_reader(cls):
    def reader(paths, *, parallelism: int = -1, **kw) -> Dataset:
        return read_datasource(cls(paths, **kw), parallelism=parallelism)

    return reader


from ray_tpu.data.datasource import (  # noqa: E402
    BinaryDatasource,
    CSVDatasource,
    ImageDatasource,
    JSONDatasource,
    NumpyDatasource,
    ParquetDatasource,
)

read_parquet = _file_reader(ParquetDatasource)
read_csv = _file_reader(CSVDatasource)
read_json = _file_reader(JSONDatasource)
read_numpy = _file_reader(NumpyDatasource)
read_binary_files = _file_reader(BinaryDatasource)


def read_images(paths, *, size=None, mode=None, parallelism: int = -1) -> Dataset:
    return read_datasource(ImageDatasource(paths, size=size, mode=mode), parallelism=parallelism)
