"""Streaming execution of dataset plans.

Reference parity: python/ray/data/_internal/execution/streaming_executor.py
(:67,149,447) — operators move ObjectRef[Block]s, not blocks; concurrency
is bounded per operator (backpressure). Here the pipeline is pull-driven:
downstream demand (iter_batches consuming) is what triggers upstream task
submission, with a sliding in-flight window per stage standing in for the
reference's resource-budget backpressure policies.
"""

from __future__ import annotations

import collections
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator

import numpy as np

import ray_tpu
from ray_tpu.data.block import Block, BlockAccessor

# ----------------------------------------------------------------------
# logical ops (a linear plan; reference: _internal/logical/operators/)
# ----------------------------------------------------------------------
@dataclass
class MapSpec:
    kind: str  # map_batches | map | filter | flat_map
    fn: Any  # callable or callable class
    fn_args: tuple = ()
    fn_kwargs: dict = field(default_factory=dict)
    fn_constructor_args: tuple = ()
    fn_constructor_kwargs: dict = field(default_factory=dict)
    batch_size: int | None = None
    batch_format: str = "numpy"
    concurrency: int | None = None
    num_cpus: float = 1.0
    zero_copy_batch: bool = False

    @property
    def is_actor_fn(self) -> bool:
        return isinstance(self.fn, type)


@dataclass
class AllToAllSpec:
    kind: str  # repartition | random_shuffle | sort
    options: dict = field(default_factory=dict)


@dataclass
class LimitSpec:
    n: int


# ----------------------------------------------------------------------
# remote kernels
# ----------------------------------------------------------------------
@ray_tpu.remote
def _exec_read_task(task) -> Block:
    return BlockAccessor.concat(list(task()))


def _apply_map(block: Block, spec: MapSpec, fn) -> Block:
    acc = BlockAccessor(block)
    if spec.kind == "map_batches":
        n = acc.num_rows()
        if n == 0:  # empty blocks pass through; user fns assume rows
            return block
        out_blocks = []
        bs = spec.batch_size or n
        for s in range(0, n, bs):
            sub = BlockAccessor(acc.slice(s, min(s + bs, n)))
            batch = sub.to_batch(spec.batch_format)
            res = fn(batch, *spec.fn_args, **spec.fn_kwargs)
            out_blocks.append(BlockAccessor.batch_to_block(res))
        return BlockAccessor.concat(out_blocks)
    if spec.kind == "map":
        rows = [fn(r, *spec.fn_args, **spec.fn_kwargs) for r in acc.iter_rows()]
        return BlockAccessor.rows_to_block(rows)
    if spec.kind == "filter":
        rows = [r for r in acc.iter_rows() if fn(r, *spec.fn_args, **spec.fn_kwargs)]
        return BlockAccessor.rows_to_block(rows) if rows else acc.slice(0, 0)
    if spec.kind == "flat_map":
        rows = [o for r in acc.iter_rows() for o in fn(r, *spec.fn_args, **spec.fn_kwargs)]
        return BlockAccessor.rows_to_block(rows) if rows else acc.slice(0, 0)
    raise ValueError(spec.kind)


@ray_tpu.remote
def _exec_map_task(block: Block, spec: MapSpec) -> Block:
    return _apply_map(block, spec, spec.fn)


@ray_tpu.remote
class _MapActor:
    """Actor-pool worker holding one instance of the user's callable class
    (reference: actor_pool_map_operator.py)."""

    def __init__(self, spec: MapSpec):
        self.spec = spec
        self.fn = spec.fn(*spec.fn_constructor_args, **spec.fn_constructor_kwargs)

    def apply(self, block: Block) -> Block:
        return _apply_map(block, self.spec, self.fn)


@ray_tpu.remote
def _slice_into(block: Block, n: int, shuffle_seed=None) -> list[Block]:
    acc = BlockAccessor(block)
    rows = acc.num_rows()
    if shuffle_seed is not None:
        rng = np.random.default_rng(shuffle_seed)
        assignment = rng.integers(0, n, rows)
        return [acc.take_indices(np.nonzero(assignment == i)[0]) for i in range(n)]
    bounds = [round(i * rows / n) for i in range(n + 1)]
    return [acc.slice(bounds[i], bounds[i + 1]) for i in range(n)]


@ray_tpu.remote
def _merge_blocks(*blocks: Block) -> Block:
    return BlockAccessor.concat(list(blocks))


@ray_tpu.remote
def _merge_shuffle(seed, *blocks: Block) -> Block:
    out = BlockAccessor.concat(list(blocks))
    rng = np.random.default_rng(seed)
    return BlockAccessor(out).take_indices(rng.permutation(out.num_rows))


@ray_tpu.remote
def _partition_by_bounds(block: Block, key: str, bounds: list, descending: bool) -> list[Block]:
    acc = BlockAccessor(block)
    col = acc.to_numpy([key])[key]
    idx = [[] for _ in range(len(bounds) + 1)]
    for i, v in enumerate(col):
        j = int(np.searchsorted(bounds, v, side="right"))
        idx[j].append(i)
    parts = [acc.take_indices(np.array(ix, dtype=np.int64)) for ix in idx]
    return parts[::-1] if descending else parts


@ray_tpu.remote
def _sort_block(block: Block, key: str, descending: bool) -> Block:
    acc = BlockAccessor(block)
    col = acc.to_numpy([key])[key]
    order = np.argsort(col, kind="stable")
    if descending:
        order = order[::-1]
    return acc.take_indices(order)


@ray_tpu.remote
def _hash_partition_block(block: Block, keys: list, n: int) -> list[Block]:
    """Split one block into n buckets by key hash (native kernels:
    _native/hashing.cpp; numpy fallback when no compiler)."""
    from ray_tpu._native import combine_hashes, hash_column, partition_indices

    acc = BlockAccessor(block)
    if acc.num_rows() == 0:
        return [block] * n if n > 1 else block
    h = hash_column(block.column(keys[0]))
    for k in keys[1:]:
        h = combine_hashes(h, hash_column(block.column(k)))
    idx, counts = partition_indices(h, n)
    out, start = [], 0
    for c in counts:
        out.append(acc.take_indices(idx[start : start + int(c)]))
        start += int(c)
    return out if n > 1 else out[0]


_ARROW_JOIN_TYPES = {
    "inner": "inner",
    "left": "left outer",
    "right": "right outer",
    "outer": "full outer",
    "full": "full outer",
}


@ray_tpu.remote
def _join_buckets(how: str, keys: list, n_left: int, *blocks: Block) -> Block:
    """Join one aligned bucket pair: blocks[:n_left] vs blocks[n_left:]."""
    left = BlockAccessor.concat(list(blocks[:n_left]))
    right = BlockAccessor.concat(list(blocks[n_left:]))
    join_type = _ARROW_JOIN_TYPES.get(how, how)
    return left.join(right, keys=keys, join_type=join_type)


@ray_tpu.remote
def _sample_block(block: Block, key: str, k: int):
    acc = BlockAccessor(block)
    col = acc.to_numpy([key])[key]
    if len(col) <= k:
        return list(col)
    rng = np.random.default_rng(0)
    return list(rng.choice(col, size=k, replace=False))


# ----------------------------------------------------------------------
# streaming pipeline
# ----------------------------------------------------------------------
class _OpResourcePool:
    """Process-wide memory pool DYNAMICALLY shared by every active stage
    (reference: streaming_executor_state.py:745 under_resource_limits over
    resource_manager.py's per-op budgets): each live OpBudget reports its
    estimated in-flight bytes; a stage's share is whatever the pool still
    has, so one active op can use the whole budget while an idle pipeline
    neighbor releases its claim — instead of the static 1/num_stages
    split."""

    def __init__(self):
        import threading

        self._lock = threading.Lock()
        self._usage: dict[int, int] = {}  # id(OpBudget) -> est. in-flight bytes

    def report(self, op_id: int, inflight_bytes: int):
        with self._lock:
            self._usage[op_id] = int(inflight_bytes)

    def release(self, op_id: int):
        with self._lock:
            self._usage.pop(op_id, None)

    def available(self, op_id: int, total_budget: int) -> int:
        with self._lock:
            others = sum(v for k, v in self._usage.items() if k != op_id)
        return max(0, total_budget - others)


_op_pool = _OpResourcePool()


class OpBudget:
    """Resource-aware in-flight budget for one pipeline stage.

    Replaces the fixed window the round-1 review flagged (reference:
    _internal/execution/streaming_executor_state.py:745 under_resource
    _limits + resource_manager.py). Constraints, re-evaluated as blocks
    are observed:
    - CPU: in-flight tasks <= cluster CPUs / task num_cpus (+ headroom),
    - memory: in-flight bytes <= the share of the GLOBAL object-store
      budget the other active stages are not using (running mean of
      observed block sizes x in-flight count, reported to _op_pool).
    An explicit user `concurrency=` wins outright.
    """

    MIN_WINDOW = 2
    MAX_WINDOW = 64

    def __init__(self, num_cpus_per_task: float = 1.0, explicit: int | None = None, num_stages: int = 1):
        self.explicit = explicit
        self._block_bytes_sum = 0
        self._block_count = 0
        try:
            import ray_tpu as _rt
            from ray_tpu._config import get_config

            cpus = float(_rt.cluster_resources().get("CPU", 4))
            store_budget = get_config().object_store_memory
        except Exception:
            cpus, store_budget = 4.0, 2 << 30
        self._cpu_cap = max(self.MIN_WINDOW, int(cpus / max(num_cpus_per_task, 0.25)) + 1)
        # the pool-wide memory budget; this op's share is computed live
        self._total_budget = max(64 << 20, store_budget // 2)
        self._floor = max(64 << 20, self._total_budget // (4 * max(num_stages, 1)))

    def _mean_block(self) -> float:
        return self._block_bytes_sum / self._block_count if self._block_count else 0.0

    def set_inflight(self, n: int):
        """Report this stage's in-flight estimate to the shared pool."""
        _op_pool.report(id(self), int(n * self._mean_block()))

    def close(self):
        _op_pool.release(id(self))

    def try_observe(self, ref) -> bool:
        """Record a block's size if it is sealed in the store yet; returns
        whether it was (unsealed blocks are retried on later ticks so the
        big slow blocks are not systematically missed)."""
        try:
            from ray_tpu.core import context

            entry = context.get_client().store.try_get_entry(ref.id)
            size = entry.size() if entry is not None else 0
        except Exception:
            return True  # unobservable: don't retry forever
        if size <= 0:
            return False
        self._block_bytes_sum += size
        self._block_count += 1
        return True

    @property
    def window(self) -> int:
        if self.explicit:
            return self.explicit
        w = self._cpu_cap
        if self._block_count:
            mean = self._mean_block()
            # dynamic share: whatever the other active stages aren't
            # using right now, never below a per-stage floor (liveness)
            share = max(self._floor, _op_pool.available(id(self), self._total_budget))
            w = min(w, int(share / max(mean, 1)))
        return max(self.MIN_WINDOW, min(self.MAX_WINDOW, w))


def _windowed(submits: Iterator, budget: "OpBudget | int"):
    """Submit lazily, keep <= budget.window tasks in flight, yield in
    order. The budget adapts to block sizes observed as yielded blocks
    seal in the store (checked on later ticks — a just-yielded block is
    usually still running)."""
    if isinstance(budget, int):
        budget = OpBudget(explicit=budget)
    inflight = collections.deque()
    unobserved = collections.deque()

    def sweep():
        for _ in range(len(unobserved)):
            ref = unobserved.popleft()
            if not budget.try_observe(ref):
                unobserved.append(ref)

    try:
        for submit in submits:
            inflight.append(submit())
            sweep()
            budget.set_inflight(len(inflight) + len(unobserved))
            while len(inflight) >= budget.window:
                ref = inflight.popleft()
                unobserved.append(ref)
                yield ref
        while inflight:
            yield inflight.popleft()
    finally:
        budget.close()  # release this stage's pool claim


@ray_tpu.remote
def _split_block(block: Block, n: int):
    """Split one oversized block into n row-balanced chunks (dynamic block
    splitting; reference: _internal/execution block splitting at
    DataContext.target_max_block_size). take() (not slice()) so each chunk
    materializes its OWN buffers — an arrow zero-copy slice would ship the
    full parent buffer with every chunk, defeating the split."""
    acc = BlockAccessor(block)
    rows = acc.num_rows()
    bounds = [round(i * rows / n) for i in range(n + 1)]
    return tuple(acc.take_indices(np.arange(bounds[i], bounds[i + 1])) for i in range(n))


def _split_oversized(upstream: Iterator, target_bytes: int) -> Iterator:
    """Transparently replace any block that SEALED above target_bytes with
    ~target-sized chunks. Unsealed blocks park briefly (re-checked each
    tick) so slow big blocks are not systematically missed; stragglers
    split at stream end."""
    from ray_tpu.core import context, direct

    def entry_size(ref):
        """-1 = still running; 0 = completed small (owned/inline — below
        the split target by construction); >0 = sealed store entry size."""
        k = ref.id.binary()
        ready = direct.owned_ready(k)
        if ready is True:
            return 0  # direct-plane inline result: < 100KB by protocol
        if ready is False:
            return -1  # direct call still in flight
        try:
            entry = context.get_client().store.try_get_entry(ref.id)
            return entry.size() if entry is not None else -1
        except Exception:
            return 0

    def maybe_split(ref, size):
        n = -(-size // target_bytes)
        if n <= 1:
            return [ref]
        return list(_split_block.options(num_returns=int(n)).remote(ref, int(n)))

    # FIFO with head-of-line gating: block order is part of Dataset
    # semantics, so a block whose size is still unknown holds later ones
    # back (they are already submitted upstream, so execution still
    # overlaps; only the yield order waits)
    pending = collections.deque()
    for ref in upstream:
        pending.append(ref)
        while pending:
            size = entry_size(pending[0])
            if size < 0:
                break  # head still running; keep order
            yield from maybe_split(pending.popleft(), size)
    import ray_tpu as rt

    while pending:
        r = pending.popleft()
        size = entry_size(r)
        if size < 0:
            rt.wait([r], num_returns=1, timeout=None)  # force seal
            size = max(entry_size(r), 0)
        yield from maybe_split(r, size)


def execute_plan(source_tasks: list, ops: list) -> Iterator:
    """Returns an iterator of ObjectRef[Block]. Pulling drives execution."""
    from ray_tpu._config import get_config

    num_stages = 1 + sum(isinstance(op, MapSpec) for op in ops)
    target = get_config().target_max_block_size
    stream: Iterator = _windowed(
        (lambda t=t: _exec_read_task.remote(t) for t in source_tasks),
        OpBudget(num_stages=num_stages),
    )
    if target > 0:
        stream = _split_oversized(stream, target)
    for op in ops:
        if isinstance(op, MapSpec):
            stream = _map_stage(stream, op, num_stages)
            if target > 0:
                stream = _split_oversized(stream, target)
        elif isinstance(op, LimitSpec):
            stream = _limit_stage(stream, op.n)
        elif isinstance(op, AllToAllSpec):
            stream = _all_to_all_stage(stream, op)
        else:
            raise TypeError(f"unknown op {op}")
    return stream


def _map_stage(upstream: Iterator, spec: MapSpec, num_stages: int = 1) -> Iterator:
    if spec.is_actor_fn:
        n_actors = spec.concurrency or 2
        window = max(spec.concurrency or 0, n_actors * 2)  # int: actor pool depth
    elif spec.concurrency:
        window = spec.concurrency  # explicit user bound wins outright
    else:
        window = OpBudget(num_cpus_per_task=spec.num_cpus, num_stages=num_stages)
    if spec.is_actor_fn:
        actors = [_MapActor.options(num_cpus=spec.num_cpus).remote(spec) for _ in range(n_actors)]
        rr = iter(range(10**12))
        submitted: list = []

        def submits():
            for ref in upstream:
                def sub(ref=ref):
                    out = actors[next(rr) % n_actors].apply.remote(ref)
                    submitted.append(out)
                    return out

                yield sub

        def gen():
            try:
                yield from _windowed(submits(), window)
            finally:
                # results must be sealed in the object store before the
                # producing actors die, else consumers see ActorDiedError
                try:
                    ray_tpu.wait(submitted, num_returns=len(submitted), timeout=None)
                except Exception:
                    pass
                for a in actors:
                    try:
                        ray_tpu.kill(a)
                    except Exception:
                        pass

        return gen()

    task = _exec_map_task.options(num_cpus=spec.num_cpus)

    def submit_local(ref):
        """Prefer the node holding the input block (soft affinity: falls
        back to any node if that one is busy/gone) — the map task then
        attaches the block's shm segment zero-copy instead of pulling it
        over the transfer service (reference: locality-aware dispatch in
        the streaming executor)."""
        loc = _block_location(ref)
        if loc is not None:
            from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

            return task.options(
                num_cpus=spec.num_cpus,
                scheduling_strategy=NodeAffinitySchedulingStrategy(node_id=loc, soft=True),
            ).remote(ref, spec)
        return task.remote(ref, spec)

    def submits():
        for ref in upstream:
            yield lambda ref=ref: submit_local(ref)

    return _windowed(submits(), window)


def _block_location(ref) -> str | None:
    """Node-id hex of the block's primary copy, if known (sealed)."""
    try:
        from ray_tpu.core import context as _ctx

        return _ctx.get_client().object_locations([ref.id]).get(ref.id.hex())
    except Exception:
        return None


@ray_tpu.remote
def _block_rows(block: Block) -> int:
    return block.num_rows


@ray_tpu.remote
def _head_block(block: Block, n: int) -> Block:
    return BlockAccessor(block).slice(0, n)


def _limit_stage(upstream: Iterator, n: int) -> Iterator:
    remaining = n
    for ref in upstream:
        if remaining <= 0:
            break
        rows = ray_tpu.get(_block_rows.remote(ref))
        if rows <= remaining:
            remaining -= rows
            yield ref  # pass-through: no payload round-trip off the store
        else:
            yield _head_block.remote(ref, remaining)
            remaining = 0


def _all_to_all_stage(upstream: Iterator, spec: AllToAllSpec) -> Iterator:
    refs = list(upstream)  # barrier: all-to-all needs the full input
    kind = spec.kind
    if kind == "repartition":
        n = spec.options["num_blocks"]
        if n == 1:
            yield _merge_blocks.remote(*refs)
            return
        parts = [_slice_into.options(num_returns=n).remote(r, n) for r in refs]
        for i in range(n):
            yield _merge_blocks.remote(*[p[i] for p in parts])
    elif kind == "random_shuffle":
        import os as _os

        seed = spec.options.get("seed")
        n = max(len(refs), 1)
        # seed=None draws fresh entropy: re-shuffles differ per epoch/run
        base = seed if seed is not None else int.from_bytes(_os.urandom(4), "little")
        if n == 1:
            yield _merge_shuffle.remote(base, *refs)
            return
        parts = [
            _slice_into.options(num_returns=n).remote(r, n, base + 17 * i) for i, r in enumerate(refs)
        ]
        for i in range(n):
            yield _merge_shuffle.remote(base + i, *[p[i] for p in parts])
    elif kind == "sort":
        key = spec.options["key"]
        desc = spec.options.get("descending", False)
        n = len(refs)
        if n == 0:
            return
        if n > 1:
            sample_refs = [_sample_block.remote(ref, key, 16) for ref in refs]
            samples = sorted(s for chunk in ray_tpu.get(sample_refs) for s in chunk)
            m = len(samples)
            bounds = [samples[min(round(i * m / n), m - 1)] for i in range(1, n)] if samples else []
        if n == 1 or not bounds:
            yield _sort_block.remote(_merge_blocks.remote(*refs), key, desc)
            return
        parts = [
            _partition_by_bounds.options(num_returns=n).remote(r, key, bounds, desc) for r in refs
        ]
        for i in range(n):
            yield _sort_block.remote(_merge_blocks.remote(*[p[i] for p in parts]), key, desc)
    else:
        raise ValueError(kind)
