"""Datasources: pluggable readers producing ReadTasks.

Reference parity: python/ray/data/datasource/ + _internal/datasource/
(parquet, csv, json, numpy, images, binary, range). A ReadTask is a
zero-arg callable executed as a remote task that yields Blocks; planning
(file listing, splitting) happens on the driver.
"""

from __future__ import annotations

import glob as _glob
import os
from dataclasses import dataclass
from typing import Callable, Iterator

import numpy as np

from ray_tpu.data.block import Block, BlockAccessor


@dataclass
class ReadTask:
    fn: Callable[[], Iterator[Block]]
    num_rows: int | None = None  # estimate for planning

    def __call__(self):
        return self.fn()


class Datasource:
    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        raise NotImplementedError

    def estimated_num_rows(self) -> int | None:
        return None


class RangeDatasource(Datasource):
    def __init__(self, n: int, use_tensor: bool = False):
        self.n = n
        self.use_tensor = use_tensor

    def estimated_num_rows(self):
        return self.n

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        tasks = []
        chunk = max(1, self.n // max(parallelism, 1))
        start = 0
        while start < self.n:
            end = min(start + chunk, self.n)
            if self.n - end < max(1, chunk // 4):  # avoid tiny tail block
                end = self.n

            def fn(s=start, e=end):
                yield BlockAccessor.batch_to_block({"id": np.arange(s, e, dtype=np.int64)})

            tasks.append(ReadTask(fn, num_rows=end - start))
            start = end
        return tasks


class ItemsDatasource(Datasource):
    def __init__(self, items: list):
        self.items = list(items)

    def estimated_num_rows(self):
        return len(self.items)

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        n = len(self.items)
        if n == 0:
            return [ReadTask(lambda: iter([BlockAccessor.rows_to_block([])]), num_rows=0)]
        chunk = max(1, n // max(parallelism, 1))
        tasks = []
        for s in range(0, n, chunk):
            part = self.items[s : s + chunk]

            def fn(part=part):
                if part and isinstance(part[0], dict):
                    yield BlockAccessor.rows_to_block(part)
                else:
                    yield BlockAccessor.batch_to_block({"item": part})

            tasks.append(ReadTask(fn, num_rows=len(part)))
        return tasks


class BlocksDatasource(Datasource):
    """From in-memory batches (from_numpy / from_pandas / from_arrow)."""

    def __init__(self, batches: list):
        self.blocks = [BlockAccessor.batch_to_block(b) for b in batches]

    def estimated_num_rows(self):
        return sum(b.num_rows for b in self.blocks)

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        return [ReadTask(lambda b=b: iter([b]), num_rows=b.num_rows) for b in self.blocks]


def _expand_paths(paths, suffix: str | None = None) -> list[str]:
    if isinstance(paths, str):
        paths = [paths]
    out = []
    for p in paths:
        if os.path.isdir(p):
            pat = os.path.join(p, "**", f"*{suffix}" if suffix else "*")
            out.extend(sorted(f for f in _glob.glob(pat, recursive=True) if os.path.isfile(f)))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(f for f in _glob.glob(p) if os.path.isfile(f)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched {paths}")
    return out


class FileDatasource(Datasource):
    suffix: str | None = None

    def __init__(self, paths, **read_kwargs):
        self.paths = _expand_paths(paths, self.suffix)
        self.read_kwargs = read_kwargs

    def read_file(self, path: str) -> Iterator[Block]:
        raise NotImplementedError

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        files = self.paths
        k = max(1, len(files) // max(parallelism, 1))
        tasks = []
        for s in range(0, len(files), k):
            group = files[s : s + k]

            def fn(group=group):
                for f in group:
                    yield from self.read_file(f)

            tasks.append(ReadTask(fn))
        return tasks


class ParquetDatasource(FileDatasource):
    suffix = ".parquet"

    def read_file(self, path):
        import pyarrow.parquet as pq

        yield pq.read_table(path, **self.read_kwargs)


class CSVDatasource(FileDatasource):
    suffix = ".csv"

    def read_file(self, path):
        import pyarrow.csv as pacsv

        yield pacsv.read_csv(path, **self.read_kwargs)


class JSONDatasource(FileDatasource):
    suffix = ".json"

    def read_file(self, path):
        import pyarrow.json as pajson

        yield pajson.read_json(path, **self.read_kwargs)


class NumpyDatasource(FileDatasource):
    suffix = ".npy"

    def read_file(self, path):
        arr = np.load(path, allow_pickle=False)
        yield BlockAccessor.batch_to_block({"data": arr})


class BinaryDatasource(FileDatasource):
    def read_file(self, path):
        with open(path, "rb") as f:
            data = f.read()
        yield BlockAccessor.batch_to_block({"bytes": [data], "path": [path]})


class ImageDatasource(FileDatasource):
    """Requires PIL (baked in)."""

    def __init__(self, paths, size: tuple[int, int] | None = None, mode: str | None = None):
        super().__init__(paths)
        self.size = size
        self.mode = mode

    def read_file(self, path):
        from PIL import Image

        img = Image.open(path)
        if self.mode:
            img = img.convert(self.mode)
        if self.size:
            img = img.resize(self.size)
        yield BlockAccessor.batch_to_block({"image": np.asarray(img)[None], "path": [path]})
