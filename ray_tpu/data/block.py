"""Blocks: the unit of data movement — Arrow tables in the object store.

Reference parity: python/ray/data/block.py + _internal/arrow_block.py —
a Dataset is a list of ObjectRef[Block]; only refs flow through the
executor, block payloads stay in the (shared-memory) object store.
"""

from __future__ import annotations

from typing import Any, Iterable

import numpy as np
import pyarrow as pa

Block = pa.Table


class BlockAccessor:
    """Format bridge + row-wise ops over one Arrow block."""

    def __init__(self, block: Block):
        self.block = block

    # ---------------- construction ----------------
    @staticmethod
    def batch_to_block(batch: Any) -> Block:
        """dict-of-arrays | pandas | arrow | list-of-rows -> Arrow table."""
        if isinstance(batch, pa.Table):
            return batch
        if isinstance(batch, dict):
            cols = {}
            for k, v in batch.items():
                v = np.asarray(v) if not isinstance(v, (pa.Array, pa.ChunkedArray, list)) else v
                if isinstance(v, np.ndarray) and v.ndim > 1:
                    # tensor column: list-of-lists arrow representation
                    cols[k] = pa.array(list(v))
                else:
                    cols[k] = v
            return pa.table(cols)
        try:
            import pandas as pd

            if isinstance(batch, pd.DataFrame):
                return pa.Table.from_pandas(batch, preserve_index=False)
        except ImportError:
            pass
        if isinstance(batch, list):
            if batch and isinstance(batch[0], dict):
                return pa.Table.from_pylist(batch)
            return pa.table({"item": pa.array(batch)})
        if isinstance(batch, np.ndarray):
            return BlockAccessor.batch_to_block({"data": batch})
        raise TypeError(f"cannot convert {type(batch)} to a block")

    @staticmethod
    def rows_to_block(rows: list[dict]) -> Block:
        return pa.Table.from_pylist(rows)

    # ---------------- properties ----------------
    def num_rows(self) -> int:
        return self.block.num_rows

    def size_bytes(self) -> int:
        return self.block.nbytes

    def schema(self):
        return self.block.schema

    # ---------------- conversion ----------------
    def to_arrow(self) -> pa.Table:
        return self.block

    def to_pandas(self):
        return self.block.to_pandas()

    def to_numpy(self, columns=None) -> dict[str, np.ndarray]:
        cols = columns or self.block.column_names
        out = {}
        for c in cols:
            col = self.block.column(c)
            try:
                out[c] = col.to_numpy(zero_copy_only=False)
            except (pa.ArrowInvalid, NotImplementedError):
                out[c] = np.array(col.to_pylist(), dtype=object)
            if out[c].dtype == object and len(out[c]) and isinstance(out[c][0], (list, np.ndarray)):
                try:
                    out[c] = np.stack([np.asarray(x) for x in out[c]])
                except ValueError:
                    pass
        return out

    def to_batch(self, batch_format: str):
        if batch_format in ("numpy", "default"):
            return self.to_numpy()
        if batch_format == "pandas":
            return self.to_pandas()
        if batch_format in ("pyarrow", "arrow"):
            return self.block
        raise ValueError(f"unknown batch_format {batch_format!r}")

    # ---------------- row ops ----------------
    def iter_rows(self) -> Iterable[dict]:
        for batch in self.block.to_batches():
            yield from batch.to_pylist()

    def slice(self, start: int, end: int) -> Block:
        return self.block.slice(start, end - start)

    def take_indices(self, idx) -> Block:
        return self.block.take(pa.array(idx))

    @staticmethod
    def concat(blocks: list[Block]) -> Block:
        blocks = [b for b in blocks if b.num_rows > 0] or blocks[:1]
        if not blocks:
            return pa.table({})
        return pa.concat_tables(blocks, promote_options="default")


def block_size_rows(block: Block) -> int:
    return block.num_rows
