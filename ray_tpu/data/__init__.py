"""ray_tpu.data: streaming distributed datasets.

Reference parity: python/ray/data (70 KLoC engine, SURVEY.md §2.4/§3.7) —
lazy plans over Arrow blocks in the shared-memory object store, executed
by a pull-driven streaming pipeline with bounded in-flight windows;
feeds ray_tpu.train via streaming_split / get_dataset_shard.
"""

from ray_tpu.util.usage import record_library_usage as _rlu

_rlu("data")

from ray_tpu.data.block import Block, BlockAccessor
from ray_tpu.data.dataset import (
    Dataset,
    GroupedData,
    MaterializedDataset,
    from_arrow,
    from_items,
    from_numpy,
    from_pandas,
    range,
    read_binary_files,
    read_csv,
    read_datasource,
    read_images,
    read_json,
    read_numpy,
    read_parquet,
)
from ray_tpu.data.datasource import Datasource, ReadTask
from ray_tpu.data.iterator import DataIterator

__all__ = [
    "Block",
    "BlockAccessor",
    "DataIterator",
    "Dataset",
    "Datasource",
    "GroupedData",
    "MaterializedDataset",
    "ReadTask",
    "from_arrow",
    "from_items",
    "from_numpy",
    "from_pandas",
    "range",
    "read_binary_files",
    "read_csv",
    "read_datasource",
    "read_images",
    "read_json",
    "read_numpy",
    "read_parquet",
]
