"""Deterministic fault injection for the runtime's serving planes.

Generalizes ``core/rpc_chaos.py`` (reference parity: src/ray/rpc/
rpc_chaos.h:24 RpcFailureManager — per-method delay/failure injection
from testing config) from the head<->node-agent transport into ONE
seeded, rule-based plane whose injection points reach everything the
serving fleet's failure semantics depend on:

==================  =====================================================
site                injection point
==================  =====================================================
direct.put_owned    owner-local publish on the direct object plane
direct.get_owned_view  borrow-get of an owned object (handoff/prefix/
                    live-state fetch)
handoff.put         disagg/kvplane handoff publish (codec -> owned object)
handoff.fetch       bounded-retry handoff fetch (each ATTEMPT is a hit)
kvplane.index       every cluster prefix-index RPC (filter with methods=)
kvplane.prefetch    one predictive-prefetch round (client worker thread):
                    a DROP rule skips the round outright, a delay rule
                    models slow hot-block transfers, a raises rule faults
                    mid-round — all must leave serving token-identical
                    (prefetch is opportunism, never load-bearing)
llm.suspend         engine.suspend_request's spill decision (tiered
                    conversation KV): a DROP/raises rule degrades to a
                    typed MigrationError with the conversation still
                    RUNNING untouched; a delay rule models slow spill
serve.step          the serve replica's stepper tick (stall = delay rule,
                    kill = raises rule: the stepper dies exactly like a
                    replica crash — waiters fail, health check trips)
serve.preempt       preemption notice, SIGTERM-with-deadline-shaped: a
                    DROP rule delivers the notice (the replica starts
                    drain(mode="migrate") — live migration of in-flight
                    decode state, llm/migrate.py); a delay rule models
                    notice latency; a raises rule kills the stepper like
                    SIGKILL (no grace). Only actively-stepping replicas
                    reach the site (an idle replica has nothing to
                    evacuate).
==================  =====================================================

Rules (``inject``) can DELAY (sleep inline), DROP (``apply`` returns
False — each site maps a drop onto its native loss signal, e.g. a
dropped ``handoff.fetch`` raises ObjectLostError into the bounded-retry
loop), or RAISE a supplied exception type. ``max_hits`` bounds a rule,
``after`` skips the first N matches (fail mid-stream, not at warmup),
``methods`` filters multi-method sites like ``kvplane.index``.

Safety contract (enforced by scripts/lint_gate.py's chaos-safety gate):

- **Inert by default.** With no rule installed, ``apply()`` is a
  zero-cost passthrough (one module-flag check), so injection points can
  live on serving paths without a perf or behavior footprint.
- **Unreachable from non-test config.** Nothing under ``ray_tpu/`` may
  call ``inject()``/``seed()`` — rules only ever come from tests (the
  autouse conftest fixture clears and re-seeds the plane around every
  test so chaos runs reproduce regardless of ordering).
- **Enumerable.** Every ``chaos.apply`` call site passes a literal site
  name from ``SITES``; the gate cross-checks both directions so the
  documented surface above can never drift from the code.

Determinism: drop/fail draws use one dedicated seeded RNG (``seed``),
shared with the rpc_chaos adapter, so a chaos test's fault schedule is a
pure function of its seed and call order.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass

from ray_tpu.exceptions import serving_error


@serving_error
class ChaosError(RuntimeError):
    """Default injected fault (rules may substitute any exception type)."""


# the fixed injection surface: literal site names at every apply() call
# site under ray_tpu/ (lint_gate's chaos-safety check enforces the
# bijection). The transport adapter (core/rpc_chaos.py) keeps its own
# dynamic "rpc.<msg_type>" namespace on top.
SITES = frozenset({
    "direct.put_owned",
    "direct.get_owned_view",
    "handoff.put",
    "handoff.fetch",
    "kvplane.index",
    "kvplane.prefetch",
    "llm.suspend",
    "serve.step",
    "serve.preempt",
})

# site -> typed errors (exceptions.SERVING_ERRORS names) a fault at that
# site may surface as to a caller that exhausts its degradation path.
# lint_gate's chaos-coverage cross-check enforces three-way agreement:
# every SITES entry has a row here, every name is registered in
# SERVING_ERRORS, and every name is exercised by tests/test_llm_chaos.py
# — so a new injection site cannot land without a typed error and a test.
FAULT_MODES: dict[str, tuple[str, ...]] = {
    "direct.put_owned": ("ObjectLostError",),
    "direct.get_owned_view": ("ObjectLostError",),
    "handoff.put": ("HandoffLostError",),
    "handoff.fetch": ("HandoffLostError",),
    "kvplane.index": ("KVRouteError",),
    "kvplane.prefetch": ("ChaosError",),
    "llm.suspend": ("MigrationError",),
    "serve.step": ("StepperDiedError",),
    "serve.preempt": ("RequestMigratedError",),
}

_RPC_PREFIX = "rpc."


@dataclass
class Rule:
    delay_s: float = 0.0
    drop_prob: float = 0.0
    fail_prob: float = 0.0
    raises: object = None  # exception CLASS (instantiated per hit)
    max_hits: int | None = None  # stop applying after this many hits
    after: int = 0  # skip the first N matches (warmup passes clean)
    methods: tuple | None = None  # kvplane.index: restrict to these RPCs
    hits: int = 0  # matches that applied (delay/drop/fail evaluated)
    seen: int = 0  # matches including ones skipped by `after`


_rules: dict[str, Rule] = {}
_lock = threading.Lock()
_rng = random.Random(0)
# fast-path flag read WITHOUT the lock: no rules installed => apply() is
# a single attribute check. Only mutated under the lock.
_armed = False


def inject(
    site: str,
    *,
    delay_s: float = 0.0,
    drop_prob: float = 0.0,
    fail_prob: float = 0.0,
    raises: object = None,
    max_hits: int | None = None,
    after: int = 0,
    methods=None,
) -> Rule:
    """Install one rule for ``site`` (replacing any existing rule there).
    ``raises`` without ``fail_prob`` means fail on every hit; ``fail_prob``
    without ``raises`` raises ChaosError. Returns the live Rule so tests
    can assert on ``.hits``."""
    global _armed
    if site not in SITES and not site.startswith(_RPC_PREFIX):
        raise ValueError(f"unknown chaos site {site!r}; sites: {sorted(SITES)} or rpc.<msg_type>")
    if raises is not None and fail_prob == 0.0:
        fail_prob = 1.0
    if fail_prob > 0.0 and raises is None:
        raises = ChaosError
    if raises is not None and not (isinstance(raises, type) and issubclass(raises, BaseException)):
        raise TypeError(f"raises must be an exception class, got {raises!r}")
    rule = Rule(
        delay_s=float(delay_s), drop_prob=float(drop_prob), fail_prob=float(fail_prob),
        raises=raises, max_hits=max_hits, after=int(after),
        methods=tuple(methods) if methods else None,
    )
    with _lock:
        _rules[site] = rule
        _armed = True
    return rule


def clear(prefix: str | None = None) -> None:
    """Remove every rule (or just those whose site starts with ``prefix``)."""
    global _armed
    with _lock:
        if prefix is None:
            _rules.clear()
        else:
            for k in [k for k in _rules if k.startswith(prefix)]:
                del _rules[k]
        _armed = bool(_rules)


def seed(n: int = 0) -> None:
    """Re-seed the drop/fail RNG — chaos schedules reproduce from here."""
    global _rng
    with _lock:
        _rng = random.Random(n)


def active() -> bool:
    """True while any rule is installed (the inert-by-default flag)."""
    return _armed


def rules() -> dict[str, Rule]:
    with _lock:
        return dict(_rules)


def apply(site: str, method: str | None = None) -> bool:
    """Evaluate chaos for one event at ``site``. Returns False when the
    event must be DROPPED (the call site maps that onto its native loss
    signal); sleeps inline for delay rules; raises for fail rules. With
    no rules installed this is a single flag check — the zero-cost
    passthrough the chaos-safety gate locks."""
    if not _armed:
        return True
    with _lock:
        rule = _rules.get(site)
        if rule is None:
            return True
        if rule.methods is not None and method not in rule.methods:
            return True
        rule.seen += 1
        if rule.seen <= rule.after:
            return True
        if rule.max_hits is not None and rule.hits >= rule.max_hits:
            return True
        rule.hits += 1
        delay = rule.delay_s
        drop = rule.drop_prob > 0 and _rng.random() < rule.drop_prob
        fail = rule.fail_prob > 0 and (rule.fail_prob >= 1.0 or _rng.random() < rule.fail_prob)
        exc = rule.raises
    if delay > 0:
        time.sleep(delay)
    if fail:
        raise exc(f"chaos: injected fault at {site}" + (f".{method}" if method else ""))
    return not drop
