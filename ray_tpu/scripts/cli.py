"""`rt` CLI: status / list / summary against the freshest session dump.

Reference parity: python/ray/scripts/scripts.py:682 (`ray status`) and
`ray list ...` from util/state — collapsed to read the head's periodic
state.json snapshot (util/state.py), so it works from any shell on the
machine while a driver runs.

    python -m ray_tpu.scripts.cli status
    python -m ray_tpu.scripts.cli list nodes|actors|tasks|pgs
    python -m ray_tpu.scripts.cli summary tasks
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _load():
    from ray_tpu.util.state import load_latest_state

    snap = load_latest_state()
    if snap is None:
        print("no ray_tpu session state found under /tmp/ray_tpu/", file=sys.stderr)
        sys.exit(1)
    age = time.time() - snap.get("ts", 0)
    if age > 30:
        print(f"warning: snapshot is {age:.0f}s old (driver may have exited)", file=sys.stderr)
    return snap


def _fmt_resources(res: dict) -> str:
    return ", ".join(f"{k}={v:g}" for k, v in sorted(res.items()))


def cmd_status(_args):
    snap = _load()
    st = snap["status"]
    print(f"== ray_tpu status (session pid {snap['pid']}, {time.time() - snap['ts']:.1f}s ago) ==")
    print(f"Nodes ({len(st['nodes'])}):")
    for n in st["nodes"]:
        mark = "" if n["alive"] else " [DEAD]"
        print(f"  {n['node_id'][:12]}{mark}  workers={n['num_workers']}  "
              f"avail: {_fmt_resources(n['available'])}  total: {_fmt_resources(n['resources'])}")
    print(f"Cluster resources: {_fmt_resources(st['cluster_resources'])}")
    print(f"Available:         {_fmt_resources(st['available_resources'])}")
    if st.get("pending_demand"):
        print(f"Pending demand ({len(st['pending_demand'])} requests):")
        for r in st["pending_demand"][:10]:
            print(f"  {_fmt_resources(r)}")
    if st.get("actors"):
        print(f"Actors by state: {st['actors']}")


def cmd_list(args):
    snap = _load()
    kind = args.kind
    if kind == "nodes":
        rows = snap["status"]["nodes"]
    elif kind == "actors":
        rows = snap.get("actors_list") or []
    elif kind in ("pgs", "placement_groups"):
        rows = snap.get("placement_groups", [])
    elif kind == "tasks":
        print(json.dumps(snap.get("tasks", {}), indent=2))
        return
    elif kind == "objects":
        print(json.dumps(snap.get("objects", {}), indent=2))
        return
    else:
        print(f"unknown kind {kind}", file=sys.stderr)
        sys.exit(2)
    print(json.dumps(rows, indent=2, default=str))


def cmd_summary(args):
    snap = _load()
    if args.kind == "tasks":
        print(json.dumps(snap.get("tasks", {}), indent=2))
    else:
        print(json.dumps(snap["status"].get("actors", {}), indent=2))


def main(argv=None):
    p = argparse.ArgumentParser(prog="rt", description="ray_tpu cluster CLI")
    sub = p.add_subparsers(dest="cmd", required=True)
    sub.add_parser("status")
    lp = sub.add_parser("list")
    lp.add_argument("kind", choices=["nodes", "actors", "tasks", "objects", "pgs", "placement_groups"])
    sp = sub.add_parser("summary")
    sp.add_argument("kind", choices=["tasks", "actors"])
    args = p.parse_args(argv)
    {"status": cmd_status, "list": cmd_list, "summary": cmd_summary}[args.cmd](args)


if __name__ == "__main__":
    main()
