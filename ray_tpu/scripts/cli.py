"""`rt` CLI: status / list / summary against the freshest session dump.

Reference parity: python/ray/scripts/scripts.py:682 (`ray status`) and
`ray list ...` from util/state — collapsed to read the head's periodic
state.json snapshot (util/state.py), so it works from any shell on the
machine while a driver runs.

    python -m ray_tpu.scripts.cli status
    python -m ray_tpu.scripts.cli list nodes|actors|tasks|pgs
    python -m ray_tpu.scripts.cli summary tasks
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _load():
    from ray_tpu.util.state import load_latest_state

    snap = load_latest_state()
    if snap is None:
        print("no ray_tpu session state found under /tmp/ray_tpu/", file=sys.stderr)
        sys.exit(1)
    age = time.time() - snap.get("ts", 0)
    if age > 30:
        print(f"warning: snapshot is {age:.0f}s old (driver may have exited)", file=sys.stderr)
    return snap


def _fmt_resources(res: dict) -> str:
    return ", ".join(f"{k}={v:g}" for k, v in sorted(res.items()))


def cmd_status(_args):
    snap = _load()
    st = snap["status"]
    print(f"== ray_tpu status (session pid {snap['pid']}, {time.time() - snap['ts']:.1f}s ago) ==")
    print(f"Nodes ({len(st['nodes'])}):")
    for n in st["nodes"]:
        mark = "" if n["alive"] else " [DEAD]"
        print(f"  {n['node_id'][:12]}{mark}  workers={n['num_workers']}  "
              f"avail: {_fmt_resources(n['available'])}  total: {_fmt_resources(n['resources'])}")
    print(f"Cluster resources: {_fmt_resources(st['cluster_resources'])}")
    print(f"Available:         {_fmt_resources(st['available_resources'])}")
    if st.get("pending_demand"):
        print(f"Pending demand ({len(st['pending_demand'])} requests):")
        for r in st["pending_demand"][:10]:
            print(f"  {_fmt_resources(r)}")
    if st.get("actors"):
        print(f"Actors by state: {st['actors']}")


def cmd_list(args):
    snap = _load()
    kind = args.kind
    if kind == "nodes":
        rows = snap["status"]["nodes"]
    elif kind == "actors":
        rows = snap.get("actors_list") or []
    elif kind in ("pgs", "placement_groups"):
        rows = snap.get("placement_groups", [])
    elif kind == "tasks":
        print(json.dumps(snap.get("tasks", {}), indent=2))
        return
    elif kind == "objects":
        print(json.dumps(snap.get("objects", {}), indent=2))
        return
    else:
        print(f"unknown kind {kind}", file=sys.stderr)
        sys.exit(2)
    print(json.dumps(rows, indent=2, default=str))


def cmd_summary(args):
    snap = _load()
    if args.kind == "tasks":
        print(json.dumps(snap.get("tasks", {}), indent=2))
    else:
        print(json.dumps(snap["status"].get("actors", {}), indent=2))


def cmd_agent(args):
    """Join a running cluster as a node agent — the cross-host worker-node
    entry point (reference: `ray start --address=head:port`,
    python/ray/scripts/scripts.py). Credentials come from flags or, when
    --address is omitted, from the head's session cluster_info.json (same
    machine)."""
    import os
    import secrets

    from ray_tpu.core.node_agent import standalone_agent_main

    if args.address:
        if not args.authkey or not args.transfer_authkey:
            print("--address requires --authkey and --transfer-authkey (hex, from the head's cluster_info.json)", file=sys.stderr)
            sys.exit(2)
        host, _, port = args.address.rpartition(":")
        authkey = bytes.fromhex(args.authkey)
        transfer_key = bytes.fromhex(args.transfer_authkey)
    else:
        from ray_tpu.util.state import load_latest_cluster_info

        info = load_latest_cluster_info()
        if info is None:
            print("no running session found; pass --address/--authkey", file=sys.stderr)
            sys.exit(1)
        host, port = info["agent_address"]
        authkey = bytes.fromhex(info["authkey"])
        transfer_key = bytes.fromhex(info["transfer_authkey"])
    # a joined agent is its own "host": take a globally-unique private shm
    # namespace (pid alone could collide with the head's session pid or a
    # joined agent on another machine)
    os.environ.setdefault("RT_SHM_NS", f"{os.getpid()}j{secrets.token_hex(2)}")
    resources = {"CPU": float(args.num_cpus)}
    if args.num_tpus:
        resources["TPU"] = float(args.num_tpus)
    labels = {"ray_tpu.io/join-token": args.join_token} if args.join_token else None
    print(f"joining head at {host}:{port} with {resources}", flush=True)
    standalone_agent_main(host, int(port), authkey, transfer_key, resources, reconnect_s=args.reconnect, labels=labels)


def main(argv=None):
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    if argv[:1] == ["lint"]:
        # forwarded verbatim: argparse.REMAINDER drops options that appear
        # before the first positional, which breaks `rt lint --list-rules`
        from ray_tpu.lint.cli import main as lint_main

        sys.exit(lint_main(argv[1:]))
    p = argparse.ArgumentParser(prog="rt", description="ray_tpu cluster CLI")
    sub = p.add_subparsers(dest="cmd", required=True)
    sub.add_parser("status")
    lp = sub.add_parser("list")
    lp.add_argument("kind", choices=["nodes", "actors", "tasks", "objects", "pgs", "placement_groups"])
    sp = sub.add_parser("summary")
    sp.add_argument("kind", choices=["tasks", "actors"])
    ap = sub.add_parser("agent", help="join a running cluster as a worker node (cross-host)")
    ap.add_argument("--address", default=None, help="head agent listener host:port")
    ap.add_argument("--authkey", default=None, help="hex agent-channel authkey")
    ap.add_argument("--transfer-authkey", default=None, help="hex object-transfer authkey")
    ap.add_argument("--num-cpus", type=float, default=1.0)
    ap.add_argument("--num-tpus", type=float, default=0.0)
    ap.add_argument("--reconnect", type=float, default=60.0, help="seconds to keep redialing a lost head (head FT window)")
    ap.add_argument("--join-token", default=None, help="opaque token echoed in the hello so a provider can match this agent to its launch")
    up = sub.add_parser("up", help="launch a cluster from a YAML/JSON config (head + autoscaler)")
    up.add_argument("config")
    sub.add_parser("down", help="stop the most recent `rt up` head")
    sub.add_parser("lint", help="run tpulint, the static runtime/JAX hazard analyzer (args forwarded)", add_help=False)
    args = p.parse_args(argv)
    {"status": cmd_status, "list": cmd_list, "summary": cmd_summary, "agent": cmd_agent, "up": cmd_up, "down": cmd_down, "lint": cmd_lint}[args.cmd](args)


def cmd_lint(_args):
    # normally unreachable (main() forwards `lint` argv verbatim before
    # argparse); kept so a direct parse of "lint" still runs the default
    # check instead of dying on a missing dispatch key
    from ray_tpu.lint.cli import main as lint_main

    sys.exit(lint_main([]))


def cmd_up(args):
    from ray_tpu.autoscaler.launcher import up

    print(f"launching cluster from {args.config} (Ctrl-C / `rt down` to stop)", flush=True)
    up(args.config, block=True)


def cmd_down(_args):
    from ray_tpu.autoscaler.launcher import down

    if down():
        print("sent shutdown to the cluster head")
    else:
        print("no running `rt up` head found", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
