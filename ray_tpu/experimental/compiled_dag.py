"""Channel-compiled DAG execution: the head leaves the steady-state loop.

Reference parity: python/ray/dag/compiled_dag_node.py:1 (compile a bound
DAG once, execute many times over persistent channels) redesigned on the
shm-ring + unix-doorbell channels of ray_tpu.experimental.channels
instead of plasma mutable objects. After ``compile_channel_dag``:

    driver --chan--> actor A --chan--> actor B --chan--> driver

every ``execute`` writes the input into a pinned ring and every hop is a
~30us shm write + doorbell — no task submission, no scheduler, no head
involvement (~10x under the task round trip measured by bench_core.py).

Topology rules (v1, same-host):
  * every compute node is a method bound on an EXISTING actor handle
    (ActorMethodNode) or on a ClassNode-created actor;
  * every node consumes at least one InputNode or upstream node (the
    channel clock: a node with no in-edge would free-run);
  * all actors live on this host (abstract unix sockets + shm).
"""

from __future__ import annotations

import threading
import uuid

from ray_tpu.core.object_store import _session_tag
from ray_tpu.dag import ActorMethodNode, ClassMethodNode, ClassNode, DAGNode, InputNode
from ray_tpu.experimental.channels import (
    STOP,
    ChannelClosedError,
    ChannelError,
    ChannelFullError,
    ChannelReader,
    ChannelWriter,
    _Stop,
    _WrappedError,
)


class CompiledDagRef:
    """Future for one execute(); results are delivered in submission
    order (the rings are FIFO), so get() drains up to this ref's seq.
    The outcome is cached on the ref: repeated get() returns (or
    re-raises) the same result; only a timeout leaves it pending."""

    def __init__(self, dag: "ChannelCompiledDAG", seq: int):
        self._dag = dag
        self._seq = seq
        self._state = "pending"
        self._value = None
        self._exc: BaseException | None = None

    def get(self, timeout: float | None = None):
        if self._state == "pending":
            try:
                self._value = self._dag._read_result(self._seq, timeout)
            except TimeoutError:
                raise  # row not consumed; retry is safe
            except BaseException as e:  # noqa: BLE001
                self._state = "err"
                self._exc = e
                raise
            self._state = "ok"
        if self._state == "err":
            raise self._exc
        return self._value


class ChannelCompiledDAG:
    def __init__(self, leaves, nslots: int = 8, buffer_size_bytes: int = 256 << 10):
        self._leaves = leaves if isinstance(leaves, list) else [leaves]
        self.nslots = nslots
        self.slot_size = buffer_size_bytes
        self._dag_id = uuid.uuid4().hex[:8]
        self._broken: BaseException | None = None
        self._torn_down = False
        self._send_seq = 0
        self._read_seq = 0
        self._done: dict[int, list] = {}
        self._pending: dict = {}  # channel name -> deque of undelivered values
        self._lock = threading.Lock()  # counters + _done; NEVER held across recv
        self._drain_lock = threading.Lock()  # serializes reader draining

        schedule = self._topo_schedule()
        self._plan_and_connect(schedule)

    # ------------------------------------------------------------------
    # compile
    # ------------------------------------------------------------------
    def _topo_schedule(self) -> list[DAGNode]:
        schedule: list[DAGNode] = []
        seen: dict[int, int] = {}

        def deps_of(node):
            deps = list(node._bound_args) + list(node._bound_kwargs.values())
            if isinstance(node, ClassMethodNode):
                deps.append(node._class_node)
            return deps

        def visit(node):
            if not isinstance(node, DAGNode):
                return
            st = seen.get(id(node))
            if st == 1:
                return
            if st == 0:
                raise ValueError("cycle detected in DAG")
            seen[id(node)] = 0
            for d in deps_of(node):
                visit(d)
            seen[id(node)] = 1
            schedule.append(node)

        for lf in self._leaves:
            visit(lf)
        return schedule

    def _node_handle(self, node, boot_memo):
        if isinstance(node, ActorMethodNode):
            return node._handle
        if isinstance(node, ClassMethodNode):
            return node._class_node._execute_memo(boot_memo)
        raise ValueError(
            f"channel-compiled DAGs support actor-method nodes only, got {type(node).__name__} "
            "(plain @remote functions have no persistent process to pin a channel to)"
        )

    def _plan_and_connect(self, schedule):
        boot_memo: dict = {"__inputs__": ()}
        compute = []
        for node in schedule:
            if isinstance(node, InputNode):
                continue
            if isinstance(node, ClassNode):
                node._execute_memo(boot_memo)  # instantiate compile-time actors
                continue
            compute.append(node)
        if not compute:
            raise ValueError("empty DAG")

        for lf in self._leaves:
            if isinstance(lf, InputNode):
                raise ValueError("an InputNode cannot be a DAG output")

        tag = _session_tag()
        chan_n = 0
        # (producer key, consumer id) -> channel name; producer key is
        # id(node) or ('input', index). A node feeding the driver through
        # several leaf positions shares ONE channel; the driver fans the
        # single delivered value out to every position.
        chans: dict[tuple, str] = {}

        def chan_for(producer_key, consumer_id) -> str:
            nonlocal chan_n
            key = (producer_key, consumer_id)
            if key not in chans:
                chans[key] = f"rt{tag}_ch{self._dag_id}_{chan_n}"
                chan_n += 1
            return chans[key]

        # per-node: ordered in-channel list + arg template
        node_in: dict[int, list[str]] = {}
        node_tmpl: dict[int, list] = {}
        node_out: dict[int, list[str]] = {id(n): [] for n in compute}
        compute_ids = {id(n) for n in compute}
        self._input_chans: dict[str, int] = {}  # name -> input index
        for node in compute:
            ins: list[str] = []
            tmpl: list = []
            if node._bound_kwargs:
                raise ValueError("channel-compiled DAGs do not support kwargs binds (v1)")
            for a in node._bound_args:
                if isinstance(a, InputNode):
                    name = chan_for(("input", a.index), id(node))
                    self._input_chans.setdefault(name, a.index)
                    if name not in ins:
                        ins.append(name)
                    tmpl.append(("edge", ins.index(name)))
                elif isinstance(a, DAGNode):
                    if id(a) not in compute_ids:
                        raise ValueError(f"unsupported upstream node {type(a).__name__}")
                    name = chan_for(id(a), id(node))
                    node_out[id(a)].append(name)
                    if name not in ins:
                        ins.append(name)
                    tmpl.append(("edge", ins.index(name)))
                else:
                    tmpl.append(("const", a))
            if not ins:
                raise ValueError(
                    f"node {node._method!r} consumes no InputNode/upstream output; "
                    "a channel-compiled node needs an in-edge to clock it"
                )
            node_in[id(node)] = ins
            node_tmpl[id(node)] = tmpl

        # leaf output channels -> driver (per-leaf names may repeat when
        # the same node is listed as several outputs)
        self._output_names: list[str] = []
        for lf in self._leaves:
            name = chan_for(id(lf), "driver")
            node_out[id(lf)].append(name)
            self._output_names.append(name)
        for nid, outs in node_out.items():
            node_out[nid] = list(dict.fromkeys(outs))

        # group steps per actor (topo order preserved within each plan)
        self._handles = []
        by_actor: dict = {}
        for node in compute:
            h = self._node_handle(node, boot_memo)
            aid = h._actor_id
            if aid not in by_actor:
                by_actor[aid] = (h, [])
                self._handles.append(h)
            by_actor[aid][1].append(
                {
                    "method": node._method,
                    "in": node_in[id(node)],
                    "out": node_out[id(node)],
                    "arg_template": node_tmpl[id(node)],
                }
            )

        # push setup to every actor (parallel: each blocks until its
        # channels connect), then bring up the driver ends: writers dial
        # root actors' listeners; readers accept the leaves' writers
        setup_refs = [
            h.__rt_chan_setup__.remote(
                {"nslots": self.nslots, "slot_size": self.slot_size, "steps": steps}
            )
            for h, steps in by_actor.values()
        ]
        self._writers: dict[str, ChannelWriter] = {}
        self._readers: dict[str, ChannelReader] = {}
        try:
            for name in self._input_chans:
                self._writers[name] = ChannelWriter(name, self.nslots, self.slot_size)
            for name in dict.fromkeys(self._output_names):
                self._readers[name] = ChannelReader(name, self.nslots, self.slot_size)
            import ray_tpu

            ray_tpu.get(setup_refs, timeout=120.0)
        except BaseException:
            self._teardown_endpoints()
            raise

    # ------------------------------------------------------------------
    # execute
    # ------------------------------------------------------------------
    def execute(self, *input_args) -> CompiledDagRef:
        if self._torn_down:
            raise ChannelError("compiled DAG was torn down")
        if self._broken is not None:
            raise ChannelError(f"compiled DAG is broken: {self._broken!r}")
        with self._lock:
            # in-flight cap = output ring capacity: past it the leaves'
            # writers would stall the whole pipeline and execute() would
            # block forever waiting for a credit only get() can free
            if self._send_seq - self._read_seq >= self.nslots:
                raise ChannelError(
                    f"{self.nslots} executions already in flight; get() results "
                    "first (or compile with a larger nslots)"
                )
            # validate EVERYTHING before any send — arity, picklability,
            # slot fit — because a partial row in the input rings would
            # desync every later execution; an unexpected mid-row failure
            # after that still marks the DAG broken
            needed = max(self._input_chans.values(), default=-1) + 1
            if len(input_args) < needed:
                raise ValueError(f"compiled DAG takes {needed} inputs, got {len(input_args)}")
            import pickle as _pickle

            from ray_tpu.experimental.channels import _HDR

            payloads = {}
            for name, idx in self._input_chans.items():
                data = _pickle.dumps(input_args[idx], protocol=5)
                w = self._writers[name]
                if len(data) > w.slot_size - _HDR.size:
                    raise ChannelFullError(
                        f"input {idx} is {len(data)} bytes, exceeds slot size {w.slot_size}; "
                        "raise experimental_compile(buffer_size_bytes=...)"
                    )
                payloads[name] = data
            try:
                for name, data in payloads.items():
                    self._writers[name].send_bytes(data)
            except BaseException as e:  # noqa: BLE001 - mid-row failure poisons the rings
                self._broken = e if isinstance(e, ChannelError) else ChannelError(f"mid-row send failed: {e!r}")
                raise
            seq = self._send_seq
            self._send_seq += 1
        return CompiledDagRef(self, seq)

    def _read_result(self, seq: int, timeout: float | None):
        from collections import deque

        with self._drain_lock:
            with self._lock:
                if seq in self._done:
                    return self._unwrap(self._done.pop(seq))
                if self._broken is not None:
                    raise ChannelError(f"compiled DAG is broken: {self._broken!r}")
                if not self._pending:
                    self._pending = {n: deque() for n in self._readers}
            while True:
                with self._lock:
                    if self._read_seq > seq:
                        return self._unwrap(self._done.pop(seq))
                    row_seq = self._read_seq
                # fill each channel's buffer for this row BEFORE popping
                # any — a timeout mid-row leaves buffered values buffered,
                # so a retried get() resumes without desyncing the rings.
                # self._lock is NOT held across the blocking recv: execute()
                # and teardown() stay responsive while a get() waits.
                for name, r in self._readers.items():
                    if not self._pending[name]:
                        if timeout is not None:
                            r.sock.settimeout(timeout)
                        try:
                            self._pending[name].append(r.recv())
                        except ChannelClosedError as e:
                            with self._lock:
                                self._broken = e
                            raise
                        finally:
                            if timeout is not None and r.sock is not None:
                                r.sock.settimeout(None)
                vals = {name: self._pending[name].popleft() for name in self._readers}
                row = [vals[n] for n in self._output_names]
                with self._lock:
                    self._done[row_seq] = row
                    self._read_seq += 1

    def _unwrap(self, vals: list):
        for v in vals:
            if isinstance(v, _WrappedError):
                raise v.exc
            if isinstance(v, _Stop):
                raise ChannelError("pipeline was stopped")
        return vals if len(vals) > 1 else vals[0]

    # ------------------------------------------------------------------
    # teardown
    # ------------------------------------------------------------------
    def teardown(self, *, kill_actors: bool = False, timeout: float = 30.0):
        """Drain gracefully: STOP flows through every stage in order, the
        actor loops exit, endpoints close. Safe after failures too."""
        if self._torn_down:
            return
        self._torn_down = True
        # graceful drain only if no get() is wedged in a blocking recv:
        # otherwise skip straight to the force path, whose endpoint close
        # wakes the stuck reader with ChannelClosedError
        drained = self._drain_lock.acquire(timeout=5.0)
        try:
            if drained and self._broken is None:
                try:
                    for name in self._input_chans:
                        self._writers[name].send(STOP)
                    for r in self._readers.values():
                        if r.sock is None:
                            continue
                        r.sock.settimeout(timeout)
                        try:
                            while not isinstance(r.recv(), _Stop):
                                pass
                        except (ChannelError, TimeoutError):
                            pass
                except ChannelError:
                    pass
        finally:
            if drained:
                self._drain_lock.release()
        # force-stop any loop that did not drain (dead peers)
        import ray_tpu

        refs = []
        for h in self._handles:
            try:
                refs.append(h.__rt_chan_teardown__.remote())
            except Exception:
                pass
        for ref in refs:
            try:
                ray_tpu.get(ref, timeout=10.0)
            except Exception:
                pass
        self._teardown_endpoints()
        if kill_actors:
            for h in self._handles:
                try:
                    ray_tpu.kill(h)
                except Exception:
                    pass

    def _teardown_endpoints(self):
        for w in self._writers.values():
            try:
                w.close()
            except Exception:
                pass
        for r in self._readers.values():
            try:
                r.close()
            except Exception:
                pass


def compile_channel_dag(leaf_or_leaves, *, nslots: int = 8, buffer_size_bytes: int = 256 << 10) -> ChannelCompiledDAG:
    return ChannelCompiledDAG(leaf_or_leaves, nslots=nslots, buffer_size_bytes=buffer_size_bytes)
