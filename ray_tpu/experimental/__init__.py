from ray_tpu.experimental.device_objects import (
    DeviceRef,
    device_get,
    device_put_object,
    free_device_object,
)

__all__ = ["DeviceRef", "device_get", "device_put_object", "free_device_object"]
