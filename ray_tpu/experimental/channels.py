"""Persistent SPSC shared-memory channels for compiled DAG execution.

TPU-native redesign of the reference's compiled-graph channel layer
(reference: python/ray/experimental/channel/shared_memory_channel.py —
mutable plasma buffers + raylet-mediated readers; here: a pinned shm ring
written in place plus a raw unix-socket doorbell, no control plane on the
steady-state path).

One channel = one producer process -> one consumer process:

  * a named POSIX shm segment holding ``nslots`` fixed-size slots,
    created once at compile time and reused for every message;
  * one abstract-namespace unix stream socket (Linux: no filesystem
    litter, vanishes with the processes) carrying 1-byte doorbells
    producer->consumer ("slot N is ready") and 1-byte credits
    consumer->producer ("slot N was drained") — recv() blocking gives
    sleep-free waiting at ~20us wakeup latency, and socket EOF doubles
    as failure detection (peer death = connection reset, no timeouts).

Backpressure is credit-based: the writer starts with ``nslots`` credits
and blocks in ``send`` when the ring is full, which bounds driver
run-ahead exactly like the reference's max buffered results.

Values are pickled (protocol 5) into the slot in place; a value larger
than the slot raises ChannelFullError naming the knob to raise
(reference parity: shared_memory_channel's buffer_size_bytes).
"""

from __future__ import annotations

import errno
import os
import pickle
import socket
import struct
import threading
import time

_HDR = struct.Struct("<Q")  # payload length per slot


class ChannelError(RuntimeError):
    pass


class ChannelClosedError(ChannelError):
    """Peer went away (process death or teardown)."""


class ChannelFullError(ChannelError):
    """Value exceeds slot capacity."""


class _Stop:
    """Poison sentinel: tears the pipeline down edge by edge."""

    def __repr__(self):
        return "<channel STOP>"


STOP = _Stop()

_NOTIFY = b"n"
_CREDIT = b"c"


def _sock_addr(name: str) -> str:
    return "\0rtch-" + name  # Linux abstract namespace


def create_ring(name: str, nslots: int, slot_size: int) -> None:
    """Create (or replace) the backing shm ring. Called by the writer."""
    import _posixshmem

    total = nslots * slot_size
    flags = os.O_CREAT | os.O_RDWR
    fd = _posixshmem.shm_open("/" + name, flags, 0o600)
    try:
        os.ftruncate(fd, total)
    finally:
        os.close(fd)


def _map_ring(name: str, writable: bool):
    import mmap

    import _posixshmem

    fd = _posixshmem.shm_open("/" + name, os.O_RDWR if writable else os.O_RDONLY, 0)
    try:
        size = os.fstat(fd).st_size
        prot = mmap.PROT_READ | (mmap.PROT_WRITE if writable else 0)
        return mmap.mmap(fd, size, prot=prot)
    finally:
        os.close(fd)


def unlink_ring(name: str) -> None:
    try:
        os.unlink("/dev/shm/" + name)
    except OSError:
        pass


class _Endpoint:
    """Shared socket plumbing for both ends of a channel."""

    def __init__(self, name: str, nslots: int, slot_size: int):
        self.name = name
        self.nslots = nslots
        self.slot_size = slot_size
        self.sock: socket.socket | None = None
        self._srv: socket.socket | None = None
        self._closed = False

    # -- connection establishment -------------------------------------
    # Bind/accept are SPLIT: every reader in a plan binds its listener
    # before any writer dials, and accepts only after all the plan's
    # writers have connected. connect(2) completes against the listen
    # backlog without an accept, so cyclic actor reuse (a -> b -> a)
    # cannot deadlock two setups against each other.
    def _bind_listen(self):
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            srv.bind(_sock_addr(self.name))
        except OSError as e:
            if e.errno != errno.EADDRINUSE:
                raise
            # stale listener from a torn-down compile of the same name:
            # abstract sockets die with their process, so an in-use addr
            # means a live peer — surface it
            raise ChannelError(f"channel {self.name} already has a listener") from None
        srv.listen(1)
        self._srv = srv

    def _accept(self, timeout: float):
        srv = self._srv
        self._srv = None
        srv.settimeout(timeout)
        try:
            conn, _ = srv.accept()
        finally:
            srv.close()
        conn.setblocking(True)
        self.sock = conn

    def _connect(self, timeout: float):
        deadline = time.monotonic() + timeout
        while True:
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            try:
                s.connect(_sock_addr(self.name))
                self.sock = s
                return
            except OSError:
                s.close()
                if time.monotonic() >= deadline:
                    raise ChannelError(f"timed out connecting to channel {self.name}") from None
                time.sleep(0.005)

    def _recv_byte(self) -> bytes:
        try:
            b = self.sock.recv(1)
        except socket.timeout:
            # a timed-out wait is NOT a dead peer: state stays consistent
            # (nothing was consumed) and the caller may retry
            raise TimeoutError(f"channel {self.name}: recv timed out") from None
        except OSError as e:
            raise ChannelClosedError(f"channel {self.name}: {e}") from None
        if not b:
            raise ChannelClosedError(f"channel {self.name}: peer closed")
        return b

    def _send_byte(self, b: bytes):
        try:
            self.sock.sendall(b)
        except OSError as e:
            raise ChannelClosedError(f"channel {self.name}: {e}") from None

    def close(self):
        self._closed = True
        if self._srv is not None:
            try:
                self._srv.close()
            except OSError:
                pass
            self._srv = None
        if self.sock is not None:
            try:
                self.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None


class ChannelWriter(_Endpoint):
    """Producer end. ``listen=True`` side binds the socket; the other
    connects (compile assigns the reader as listener)."""

    def __init__(self, name: str, nslots: int = 8, slot_size: int = 256 << 10, *, create: bool = True, connect_timeout: float = 60.0):
        super().__init__(name, nslots, slot_size)
        if create:
            create_ring(name, nslots, slot_size)
        self._map = _map_ring(name, writable=True)
        self._view = memoryview(self._map)
        self._seq = 0
        self._credits = nslots
        self._lock = threading.Lock()  # send() is not re-entrant; guard misuse
        self._connect(connect_timeout)

    def send(self, value) -> None:
        self.send_bytes(pickle.dumps(value, protocol=5))

    def send_bytes(self, payload: bytes) -> None:
        """Send a pre-pickled payload (lets callers validate a whole batch
        of sends before committing any — compiled_dag.execute)."""
        if len(payload) > self.slot_size - _HDR.size:
            raise ChannelFullError(
                f"channel message of {len(payload)} bytes exceeds slot size "
                f"{self.slot_size}; raise experimental_compile(buffer_size_bytes=...)"
            )
        with self._lock:
            while self._credits == 0:
                self._recv_byte()  # blocks for a credit
                self._credits += 1
            slot = self._seq % self.nslots
            off = slot * self.slot_size
            self._view[off : off + _HDR.size] = _HDR.pack(len(payload))
            self._view[off + _HDR.size : off + _HDR.size + len(payload)] = payload
            self._seq += 1
            self._credits -= 1
            self._send_byte(_NOTIFY)

    def close(self):
        super().close()
        if getattr(self, "_view", None) is not None:
            self._view.release()
            self._view = None
        if getattr(self, "_map", None) is not None:
            self._map.close()
            self._map = None


class ChannelReader(_Endpoint):
    """Consumer end: binds the socket and waits for the writer. With
    ``eager=False`` only the listener is bound; call ``finish()`` after
    the plan's writers have dialed (two-phase runner setup)."""

    def __init__(self, name: str, nslots: int = 8, slot_size: int = 256 << 10, *, connect_timeout: float = 60.0, eager: bool = True):
        super().__init__(name, nslots, slot_size)
        self._map = None
        self._view = None
        self._seq = 0
        self._bind_listen()
        if eager:
            self.finish(connect_timeout)

    def finish(self, timeout: float = 60.0):
        self._accept(timeout)
        self._map = _map_ring(self.name, writable=False)
        self._view = memoryview(self._map)

    def recv(self):
        self._recv_byte()  # blocks for a doorbell
        slot = self._seq % self.nslots
        off = slot * self.slot_size
        (n,) = _HDR.unpack(self._view[off : off + _HDR.size])
        value = pickle.loads(self._view[off + _HDR.size : off + _HDR.size + n])
        self._seq += 1
        self._send_byte(_CREDIT)
        return value

    def close(self):
        super().close()
        if getattr(self, "_view", None) is not None:
            self._view.release()
            self._view = None
        if getattr(self, "_map", None) is not None:
            self._map.close()
            self._map = None
        unlink_ring(self.name)


class _WrappedError:
    """Carries an upstream exception through the pipeline to the driver."""

    def __init__(self, exc: BaseException, where: str):
        self.exc = exc
        self.where = where


class ChannelLoopRunner:
    """The per-actor execution loop a compiled DAG pushes into each
    participating worker (reference: compiled_dag_node's do_exec_tasks
    actor loop). Runs on a dedicated thread so the actor's normal task
    queue stays live for health checks and teardown calls.

    ``plan`` (one per actor, produced at compile):
        nslots/slot_size: ring geometry
        steps: topo-ordered list of
            {method, in: [channel names], out: [channel names],
             arg_template: ['edge:<i>' | 'const:<pickle hex>' ...]}
    In-edges are read in template order; every out-edge gets the result.
    STOP or an upstream _WrappedError short-circuits the method call and
    propagates downstream, so teardown and failures drain the whole
    pipeline without the control plane.
    """

    def __init__(self, actor_instance, plan: dict):
        self.instance = actor_instance
        self.plan = plan
        self.readers: dict[str, ChannelReader] = {}
        self.writers: dict[str, ChannelWriter] = {}
        self.thread: threading.Thread | None = None
        self.error: BaseException | None = None

    def setup(self):
        nslots = self.plan["nslots"]
        slot = self.plan["slot_size"]
        # self-edges (a step feeding a later step on the SAME actor) stay
        # in-process: steps run sequentially on one thread, so the value
        # is just queued locally — a socket to ourselves would deadlock
        # setup (accept and connect on the same thread)
        in_names = {n for s in self.plan["steps"] for n in s["in"]}
        out_names = {n for s in self.plan["steps"] for n in s["out"]}
        self.local: dict[str, list] = {n: [] for n in in_names & out_names}
        # Three-phase bring-up (see _bind_listen): bind every listener,
        # dial every writer, then accept — immune to cyclic actor reuse.
        for step in self.plan["steps"]:
            for name in step["in"]:
                if name not in self.readers and name not in self.local:
                    self.readers[name] = ChannelReader(name, nslots, slot, eager=False)
        for step in self.plan["steps"]:
            for name in step["out"]:
                if name not in self.writers and name not in self.local:
                    self.writers[name] = ChannelWriter(name, nslots, slot)
        for r in self.readers.values():
            r.finish()
        self.thread = threading.Thread(target=self._loop, name="rt-chan-loop", daemon=True)
        self.thread.start()

    def _loop(self):
        try:
            while True:
                stop = self._run_iteration()
                if stop:
                    return
        except ChannelClosedError as e:
            # a peer died mid-pipeline: poison what we can downstream
            self.error = e
            self._propagate_all(_WrappedError(e, where="channel"))
        except BaseException as e:  # noqa: BLE001
            self.error = e
        finally:
            self._close_all()

    def _recv_edge(self, name):
        if name in self.local:
            return self.local[name].pop(0)
        return self.readers[name].recv()

    def _send_edge(self, name, value):
        if name in self.local:
            self.local[name].append(value)
        else:
            self.writers[name].send(value)

    def _run_iteration(self) -> bool:
        saw_stop = False
        for step in self.plan["steps"]:
            ins = [self._recv_edge(n) for n in step["in"]]
            poison = next((v for v in ins if isinstance(v, (_Stop, _WrappedError))), None)
            if poison is not None:
                for n in step["out"]:
                    self._send_edge(n, STOP if isinstance(poison, _Stop) else poison)
                if isinstance(poison, _Stop):
                    saw_stop = True
                continue
            # template entries: ('edge', i) -> ins[i]; ('const', value)
            args = [ins[t[1]] if t[0] == "edge" else t[1] for t in step["arg_template"]]
            try:
                result = getattr(self.instance, step["method"])(*args)
            except BaseException as e:  # noqa: BLE001
                result = _WrappedError(e, where=step["method"])
            for n in step["out"]:
                self._send_edge(n, result)
        return saw_stop

    def _propagate_all(self, value):
        for w in self.writers.values():
            try:
                w.send(value)
            except ChannelError:
                pass

    def _close_all(self):
        for w in self.writers.values():
            w.close()
        for r in self.readers.values():
            r.close()

    def teardown(self, timeout: float = 10.0):
        """Force-stop: close endpoints; the loop thread exits on the next
        channel op (used when graceful STOP cannot flow, e.g. a dead
        upstream)."""
        self._close_all()
        if self.thread is not None:
            self.thread.join(timeout=timeout)
