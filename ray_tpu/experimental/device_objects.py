"""Device-object store: pass jax.Arrays between tasks/actors by reference.

Reference parity: python/ray/experimental/gpu_object_manager/
gpu_object_store.py (GPU objects held on the owning actor, moved on demand)
— re-thought for the TPU process model:

- TPU has no cross-process device-IPC (no CUDA-IPC equivalent); a device
  buffer is only addressable from the PJRT client that allocated it. The
  fast path is therefore *process locality*: a `DeviceRef` resolved in the
  owning process returns the registered jax.Array itself — zero copies,
  zero host traffic. The runtime's worker reuse + actor affinity make this
  the common case (e.g. weights shared between an LLM engine and its Serve
  replica, or between tasks pinned to one TPU actor).
- Cross-process, the owner exports once through the shm object store:
  device->host fetch on the owner, zero-copy shm attach + device_put on
  the consumer. One host copy each side — strictly better than the pickle
  round-trip of passing the array by value, and the bytes never transit
  the head process. Requires the owner to be an actor (it must be alive
  to serve the transfer; plain-task outputs should return values instead).

put/get semantics:
    ref = device_put_object(arr)          # register, zero-copy
    arr = device_get(ref)                 # owner process: the same object
    arr = device_get(ref)                 # elsewhere: shm transfer once,
                                          # then cached in-process
"""

from __future__ import annotations

import os
import threading
import uuid
from dataclasses import dataclass, field

_lock = threading.Lock()
_registry: dict[str, object] = {}  # id -> jax.Array (this process's objects)
_transfer_cache: dict[str, object] = {}  # id -> fetched copy (consumer side)


@dataclass(frozen=True)
class DeviceRef:
    """Handle to a device array registered in some process's registry."""

    object_id: str
    owner_pid: int
    shape: tuple
    dtype: str
    owner_actor: object = field(default=None, compare=False)

    def __repr__(self):
        return f"DeviceRef({self.object_id[:8]}, pid={self.owner_pid}, {self.dtype}{list(self.shape)})"


def device_put_object(arr, owner_actor=None) -> DeviceRef:
    """Register a jax.Array (or pytree leaf array) in this process's device
    registry. `owner_actor`: this actor's own handle, if the ref will be
    consumed from other processes (they fetch through it)."""
    import jax

    arr = jax.numpy.asarray(arr)
    obj_id = uuid.uuid4().hex
    with _lock:
        _registry[obj_id] = arr
    return DeviceRef(
        object_id=obj_id,
        owner_pid=os.getpid(),
        shape=tuple(arr.shape),
        dtype=str(arr.dtype),
        owner_actor=owner_actor,
    )


def device_get(ref: DeviceRef):
    """Resolve a DeviceRef to a jax.Array. Zero-copy in the owner process;
    one shm transfer (cached) elsewhere."""
    if ref.owner_pid == os.getpid():
        with _lock:
            try:
                return _registry[ref.object_id]
            except KeyError:
                raise KeyError(f"device object {ref.object_id[:8]} freed or unknown") from None
    with _lock:
        hit = _transfer_cache.get(ref.object_id)
    if hit is not None:
        return hit
    if ref.owner_actor is None:
        raise ValueError(
            "DeviceRef is being resolved outside its owner process but carries "
            "no owner_actor handle; pass owner_actor= to device_put_object"
        )
    import jax

    import ray_tpu

    host = ray_tpu.get(ref.owner_actor.__rt_device_get__.remote(ref.object_id))
    arr = jax.device_put(host)
    with _lock:
        _transfer_cache[ref.object_id] = arr
    return arr


def free_device_object(ref: DeviceRef):
    """Drop this process's registry/cache entry for the ref."""
    with _lock:
        _registry.pop(ref.object_id, None)
        _transfer_cache.pop(ref.object_id, None)


def export_for_transfer(object_id: str):
    """Owner-side export hook (wired as the builtin actor method
    __rt_device_get__, core/worker_main.py): device->host once; the
    runtime's return path writes it to shm, the consumer attaches
    zero-copy."""
    import numpy as np

    with _lock:
        arr = _registry.get(object_id)
    if arr is None:
        raise KeyError(f"device object {object_id[:8]} not registered in this process")
    return np.asarray(arr)


# ----------------------------------------------------------------------
# pytree conveniences: register/resolve whole parameter trees
# ----------------------------------------------------------------------
def device_put_tree(tree, owner_actor=None):
    import jax

    return jax.tree.map(lambda a: device_put_object(a, owner_actor=owner_actor), tree)


def device_get_tree(tree_of_refs):
    import jax

    return jax.tree.map(
        device_get, tree_of_refs, is_leaf=lambda x: isinstance(x, DeviceRef)
    )
