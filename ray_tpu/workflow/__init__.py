"""Workflows are removed (parity with reference python/ray/workflow/__init__.py:1-4,
which raises a deprecation error on import)."""

raise ImportError(
    "ray_tpu.workflow has been removed, matching the reference's deprecation "
    "of Ray Workflows. Use ray_tpu tasks/actors or ray_tpu.dag instead."
)
