"""tpulint: AST-based static analysis for the distributed runtime.

The dynamic `lock_sanitizer` (core/lock_sanitizer.py) catches ordering
inversions the test suite happens to EXECUTE; this package is its static
complement — the TPU-native analogue of the TSAN + clang-tidy pair the
reference leans on for its C++ raylet (SURVEY §5.2). A visitor core walks
each module once per rule; rules encode the runtime's own invariants
(blocking gets inside actors, dropped ObjectRefs, non-serializable remote
captures, lock-order cycles, JAX purity under jit, unbounded polls inside
deadline loops).

Usage:

    python -m ray_tpu.lint ray_tpu/              # check vs checked-in baseline
    python -m ray_tpu.lint --list-rules
    python -m ray_tpu.lint ray_tpu/ --update-baseline

Accepted pre-existing findings live in ``ray_tpu/lint/baseline.json``;
the CLI exits non-zero only on findings NOT in the baseline, so the
tier-1 self-check (tests/test_lint.py) gates new hazards without a
flag-day cleanup.
"""

from ray_tpu.lint.engine import Finding, Rule, lint_paths, lint_source  # noqa: F401
from ray_tpu.lint.rules import all_rules  # noqa: F401

DEFAULT_BASELINE = "baseline.json"  # sibling of this package's __init__
