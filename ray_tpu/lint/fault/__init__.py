"""Fault-discipline (ERR) lint catalog.

See ``ray_tpu/lint/fault/rules.py`` for the rules and
``ray_tpu/exceptions.SERVING_ERRORS`` for the typed-error taxonomy the
catalog audits against.
"""

from ray_tpu.lint.fault.rules import (  # noqa: F401
    FAULT_RULES,
    all_fault_rules,
    fault_rule_catalog,
    fault_rule_ids,
)
