"""ERR rules: fault discipline over the serving/plane paths.

ERR001  swallowed-exception        an ``except`` that neither re-raises,
                                   converts to a taxonomy type, nor
                                   counts/logs (absorbed TPL007; the old
                                   id stays a live alias for baselines
                                   and inline disables)
ERR002  non-taxonomy-raise         bare ``RuntimeError``/``ValueError``/
                                   ``Exception`` reachable (depth 2 via
                                   the callgraph) from a serving ingress,
                                   engine-step, or router root
ERR003  raise-without-cause        ``raise X(...)`` inside an ``except``
                                   block without ``from e`` (or explicit
                                   cause threading) — a dropped chain
                                   breaks the router probes
ERR004  unbounded-retry            retry-shaped ``while True`` loop
                                   (sleep + except) that neither draws a
                                   RetryBudget nor tests a deadline
ERR005  unbounded-transport-call   transport/index/object-plane call
                                   (``index_call``, ``.request()``,
                                   ``.fetch()``, ``get_owned_view``,
                                   ``ray.get``) without a bounded
                                   timeout, interprocedural through the
                                   callgraph

The discipline these rules enforce is the robustness plane's contract:
every failure surfaces as a *typed* error (``exceptions.SERVING_ERRORS``)
in a *bounded* time, and cause chains survive wrapping so the router
probes (``http_error_of``, ``migration_of``, ``is_overloaded``) can
classify them. Deliberate hazards go to the baseline with a ``why`` or an
inline ``# tpulint: disable=ERR00x`` (locally explainable).

Serving-path scoping: ERR002–005 and ERR001's broad arm only fire under
``ray_tpu/serve/``, ``ray_tpu/llm/`` and ``core/direct.py`` (the transport
the serving planes ride) — control-plane and test scaffolding raise and
wait however they like. ERR001's connection-error arm keeps TPL007's
any-path scope: a silently dropped peer death is a hazard everywhere.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ray_tpu.lint.callgraph import CallGraph, _walk_body, blocking_ray_call, dotted
from ray_tpu.lint.concur.lockset import iter_functions
from ray_tpu.lint.engine import FileContext, Finding, Rule, ScopedVisitor, call_keyword


# ---------------------------------------------------------------------------
# shared predicates
# ---------------------------------------------------------------------------
def _serving_path(path: str) -> bool:
    """Paths carrying the serving/plane discipline (see module docstring).
    Matched on posix-relative finding paths, so fixtures opt in by
    passing e.g. ``path="ray_tpu/serve/fixture.py"`` to lint_source."""
    parts = path.split("/")
    return "serve" in parts or "llm" in parts or path.endswith("core/direct.py")


# serving ingress / engine-step / router roots: a raise or unbounded wait
# reachable from one of these is client-visible by construction
_ROOT_NAMES = {
    "generate", "generate_stream", "__call__", "step", "prefill", "decode",
    "generate_from_handoff", "resume_from_migration", "resume_suspended",
    "suspend_request", "preempt", "check_health", "route",
}


def _is_root(name: str) -> bool:
    return name in _ROOT_NAMES or name.startswith("handle")


_CONN_ERRORS = {
    "ConnectionError", "ConnectionResetError", "ConnectionAbortedError",
    "ConnectionRefusedError", "BrokenPipeError",
}
_BROAD_CATCHES = {"Exception", "BaseException"}


def _names(type_expr: ast.AST | None) -> list[str]:
    """Last segments of the caught exception type(s); [] for bare except."""
    if type_expr is None:
        return []
    exprs = list(type_expr.elts) if isinstance(type_expr, ast.Tuple) else [type_expr]
    out = []
    for e in exprs:
        name = dotted(e)
        if name is not None:
            out.append(name.split(".")[-1])
    return out


# ---------------------------------------------------------------------------
# ERR001: swallowed exception (absorbed TPL007)
# ---------------------------------------------------------------------------
# teardown/eviction contexts where best-effort swallows are the
# documented idiom (the operation is already ending; there is no caller
# left to surface a typed error to) — same carve-out TPL007 made for
# plain OSError cleanup swallows
_TEARDOWN_TOKENS = (
    "shutdown", "close", "cancel", "stop", "teardown", "cleanup", "clear",
    "release", "drop", "free", "evict", "finalize", "abort",
)


def _teardown_scope(qualname: str) -> bool:
    leaf = qualname.rsplit(".", 1)[-1].lower()
    return leaf == "__del__" or any(tok in leaf for tok in _TEARDOWN_TOKENS)


def _uses_name(body: list[ast.stmt], name: str | None) -> bool:
    if name is None:
        return False
    for stmt in body:
        for n in ast.walk(stmt):
            if isinstance(n, ast.Name) and n.id == name:
                return True
    return False


def _handles_somehow(handler: ast.ExceptHandler) -> bool:
    """True when the handler observably HANDLES the exception: re-raises,
    calls anything (log/count/cleanup helpers), bumps a counter
    (AugAssign), writes shared state another path reads (assignment to an
    attribute or subscript, e.g. ``rec["error"] = True``), or lets the
    bound exception value escape (``last = e`` for a later terminal
    raise). A handler doing none of these drops the event on the floor."""
    for stmt in handler.body:
        for n in ast.walk(stmt):
            if isinstance(n, (ast.Raise, ast.Call, ast.AugAssign)):
                return True
            if isinstance(n, (ast.Assign, ast.AnnAssign)):
                targets = n.targets if isinstance(n, ast.Assign) else [n.target]
                for t in targets:
                    if any(isinstance(s, (ast.Attribute, ast.Subscript)) for s in ast.walk(t)):
                        return True
    return _uses_name(handler.body, handler.name)


def _all_trivial(body: list[ast.stmt]) -> bool:
    """Statement shapes that cannot observe the exception: pass/continue/
    break, constant expressions (docstrings), call-free returns and
    call-free assignments."""
    for stmt in body:
        if isinstance(stmt, (ast.Pass, ast.Continue, ast.Break)):
            continue
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Constant):
            continue
        if isinstance(stmt, (ast.Return, ast.Assign, ast.AnnAssign)):
            if any(isinstance(n, ast.Call) for n in ast.walk(stmt)):
                return False
            continue
        return False
    return True


class _SwallowVisitor(ScopedVisitor):
    def __init__(self, rule: Rule, ctx: FileContext):
        super().__init__()
        self.rule = rule
        self.ctx = ctx
        self.serving = _serving_path(ctx.path)
        self.out: list[Finding] = []

    def visit_Try(self, node: ast.Try):
        for handler in node.handlers:
            caught = set(_names(handler.type))
            conn = sorted(caught & _CONN_ERRORS)
            bare_body = all(
                isinstance(s, (ast.Pass, ast.Continue, ast.Break)) for s in handler.body
            )
            if conn and bare_body:
                # TPL007's arm, any path: a dropped peer-death transition
                self.out.append(self.rule.finding(
                    self.ctx, handler,
                    f"swallowed {'/'.join(conn)} with a bare pass: the peer-death event is "
                    "lost (pending work never fails over); complete/fail the in-flight "
                    "state or record why another path observes it",
                    context=self.qualname,
                ))
            elif (
                self.serving
                and self.qualname  # module level = import-guard idiom
                and not _teardown_scope(self.qualname)
                and not _handles_somehow(handler)
            ):
                # broad catches only: catching a SPECIFIC taxonomy type and
                # degrading (break/continue on GetTimeoutError in a poll
                # loop) is the bounded-degradation idiom, not a swallow
                broad = handler.type is None or bool(caught & _BROAD_CATCHES)
                if broad and _all_trivial(handler.body):
                    what = "/".join(sorted(caught)) if caught else "bare except"
                    self.out.append(self.rule.finding(
                        self.ctx, handler,
                        f"swallowed exception ({what}) on a serving path: the handler "
                        "neither re-raises, converts to a SERVING_ERRORS type, nor "
                        "counts/logs — the failure vanishes instead of surfacing typed",
                        context=self.qualname,
                    ))
        self.generic_visit(node)


class SwallowedException(Rule):
    id = "ERR001"
    name = "swallowed-exception"
    summary = (
        "except handler on a serving/plane path that neither re-raises, converts to a "
        "taxonomy type, nor counts/logs (alias: TPL007)"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        v = _SwallowVisitor(self, ctx)
        v.visit(ctx.tree)
        yield from v.out


# ---------------------------------------------------------------------------
# ERR002: non-taxonomy raise reachable from a serving root
# ---------------------------------------------------------------------------
_GENERIC_RAISES = {"RuntimeError", "ValueError", "Exception"}


def _raised_name(node: ast.Raise) -> str | None:
    exc = node.exc
    if isinstance(exc, ast.Call):
        name = dotted(exc.func)
    else:
        name = dotted(exc) if exc is not None else None
    return name.split(".")[-1] if name else None


def _reachable_raises(cg: CallGraph, fn, cls, depth: int):
    """(raise node, resolved call chain) lexically in ``fn`` or in callees
    resolvable to ``depth`` further levels (cycle-safe)."""
    out: list[tuple[ast.Raise, tuple[str, ...]]] = []
    seen = {id(fn)}

    def rec(f, c, d, chain):
        for n in _walk_body(f):
            if isinstance(n, ast.Raise) and n.exc is not None:
                out.append((n, chain))
            elif isinstance(n, ast.Call) and d > 0:
                callee = cg.resolve(n, c)
                if callee is not None and id(callee) not in seen:
                    seen.add(id(callee))
                    rec(callee, cg.class_of(callee), d - 1, chain + (callee.name,))

    rec(fn, cls, depth, ())
    return out


class NonTaxonomyRaise(Rule):
    id = "ERR002"
    name = "non-taxonomy-raise"
    summary = (
        "bare RuntimeError/ValueError/Exception raised on a path reachable from a "
        "serving ingress, engine step, or router root"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not _serving_path(ctx.path):
            return
        cg = CallGraph(ctx.tree)
        fns = list(iter_functions(ctx.tree))
        owner: dict[int, str] = {}
        for fn, _cls, qual in fns:
            for n in _walk_body(fn):
                owner.setdefault(id(n), qual)
        reported: set[int] = set()
        for fn, cls, qual in fns:
            if not _is_root(fn.name):
                continue
            for raise_node, chain in _reachable_raises(cg, fn, cls, depth=2):
                name = _raised_name(raise_node)
                if name not in _GENERIC_RAISES or id(raise_node) in reported:
                    continue
                reported.add(id(raise_node))
                via = f" via {' -> '.join(chain)}" if chain else ""
                yield self.finding(
                    ctx, raise_node,
                    f"raise {name} reachable from serving root {qual}(){via}: "
                    "client-visible failures must be SERVING_ERRORS types "
                    "(exceptions.py) so proxies/routers can classify them",
                    context=owner.get(id(raise_node), qual),
                )


# ---------------------------------------------------------------------------
# ERR003: raise inside except without cause threading
# ---------------------------------------------------------------------------
def _threads_cause(call: ast.Call, bound: list[str]) -> bool:
    """Explicit cause threading: the bound exception passed as a bare
    argument of the replacement error (``TaskError(cause=e)``,
    ``from_exception(e)``)."""
    args = list(call.args) + [kw.value for kw in call.keywords]
    return any(isinstance(a, ast.Name) and a.id in bound for a in args)


class _CauseVisitor(ScopedVisitor):
    def __init__(self, rule: Rule, ctx: FileContext):
        super().__init__()
        self.rule = rule
        self.ctx = ctx
        self.out: list[Finding] = []
        self._bound: list[str] = []  # handler-bound names, innermost last
        self._in_handler = 0

    def visit_Try(self, node: ast.Try):
        for stmt in node.body + node.orelse + node.finalbody:
            self.visit(stmt)
        for handler in node.handlers:
            self._in_handler += 1
            if handler.name:
                self._bound.append(handler.name)
            for stmt in handler.body:
                self.visit(stmt)
            if handler.name:
                self._bound.pop()
            self._in_handler -= 1

    def visit_Raise(self, node: ast.Raise):
        if (
            self._in_handler
            and isinstance(node.exc, ast.Call)
            and node.cause is None
            and not _threads_cause(node.exc, self._bound)
        ):
            name = dotted(node.exc.func) or "<exception>"
            self.out.append(self.rule.finding(
                self.ctx, node,
                f"raise {name.split('.')[-1]}(...) inside except without `from e` "
                "(or passing the caught error in): the dropped cause chain blinds "
                "the router probes (http_error_of / migration_of / is_overloaded)",
                context=self.qualname,
            ))
        self.generic_visit(node)


class RaiseWithoutCause(Rule):
    id = "ERR003"
    name = "raise-without-cause"
    summary = (
        "raise X(...) inside an except block without `from e` or explicit cause "
        "threading — wire probes lose the classification chain"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not _serving_path(ctx.path):
            return
        v = _CauseVisitor(self, ctx)
        v.visit(ctx.tree)
        yield from v.out


# ---------------------------------------------------------------------------
# ERR004: unbounded retry loop
# ---------------------------------------------------------------------------
_BOUND_TOKENS = ("deadline", "timeout", "budget", "retries", "retry", "attempt", "tries")


def _loop_is_bounded(loop: ast.While) -> bool:
    """Any identifier smelling of a bound (deadline/timeout/budget/
    attempt counter) or a RetryBudget.try_spend call inside the loop."""
    for n in ast.walk(loop):
        ident = None
        if isinstance(n, ast.Name):
            ident = n.id
        elif isinstance(n, ast.Attribute):
            ident = n.attr
        if ident is not None:
            low = ident.lower()
            if any(tok in low for tok in _BOUND_TOKENS):
                return True
        if isinstance(n, ast.Call):
            fname = dotted(n.func)
            if fname is not None and fname.split(".")[-1] == "try_spend":
                return True
    return False


class UnboundedRetryLoop(Rule):
    id = "ERR004"
    name = "unbounded-retry"
    summary = (
        "while-True retry loop (sleep + except) that neither draws from a RetryBudget "
        "nor tests a deadline — failure never surfaces in bounded time"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not _serving_path(ctx.path):
            return
        for fn, _cls, qual in iter_functions(ctx.tree):
            for node in _walk_body(fn):
                if not isinstance(node, ast.While):
                    continue
                test = node.test
                if not (isinstance(test, ast.Constant) and test.value in (True, 1)):
                    continue
                has_sleep = any(
                    isinstance(n, ast.Call)
                    and (dotted(n.func) or "").split(".")[-1] == "sleep"
                    for n in ast.walk(node)
                )
                has_retry = any(
                    isinstance(n, ast.Try) and n.handlers for n in ast.walk(node)
                )
                if has_sleep and has_retry and not _loop_is_bounded(node):
                    yield self.finding(
                        ctx, node,
                        "retry-shaped `while True` (sleep + except) with no deadline, "
                        "timeout, attempt bound, or RetryBudget draw: on a persistent "
                        "fault this path retries forever instead of failing typed",
                        context=qual,
                    )


# ---------------------------------------------------------------------------
# ERR005: unbounded transport / plane call
# ---------------------------------------------------------------------------
_PLANE_FETCH_ATTRS = {"fetch", "lookup", "publish", "register", "heartbeat"}


def _timeout_value(call: ast.Call, positional_idx: int | None = None):
    """('absent', None) when no timeout is passed; ('node', expr) with the
    passed expression otherwise. ``positional_idx`` names the positional
    slot a timeout may ride in (None = keyword-only)."""
    kw = call_keyword(call, "timeout", "timeout_s")
    if kw is not None:
        return "node", kw.value
    if positional_idx is not None and len(call.args) > positional_idx:
        return "node", call.args[positional_idx]
    return "absent", None


def _is_none(expr: ast.AST | None) -> bool:
    return isinstance(expr, ast.Constant) and expr.value is None


def _forwarded_none_params(fn) -> dict[str, str]:
    """param name -> transport label, for params defaulting to None that a
    function forwards into a transport call's timeout argument — callers
    omitting the param inherit an unbounded wait."""
    args = fn.args
    defaults = dict(zip([a.arg for a in args.args[len(args.args) - len(args.defaults):]],
                        args.defaults))
    defaults.update({a.arg: d for a, d in zip(args.kwonlyargs, args.kw_defaults) if d is not None})
    none_params = {name for name, d in defaults.items()
                   if _is_none(d) and "timeout" in name.lower()}
    if not none_params:
        return {}
    out: dict[str, str] = {}
    for n in _walk_body(fn):
        if not isinstance(n, ast.Call):
            continue
        kw = call_keyword(n, "timeout", "timeout_s")
        if kw is not None and isinstance(kw.value, ast.Name) and kw.value.id in none_params:
            label = dotted(n.func) or "<call>"
            out[kw.value.id] = f"{label}()"
    return out


def _passes_param(call: ast.Call, fn, param: str) -> bool:
    if call_keyword(call, param) is not None:
        return True
    names = [a.arg for a in fn.args.args]
    if isinstance(call.func, ast.Attribute) and names and names[0] == "self":
        names = names[1:]
    if param in names:
        return len(call.args) > names.index(param)
    return False


class UnboundedTransportCall(Rule):
    id = "ERR005"
    name = "unbounded-transport-call"
    summary = (
        "transport/index/object-plane call (index_call, .request(), .fetch(), "
        "get_owned_view, ray.get) without a bounded timeout (interprocedural)"
    )

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        if not _serving_path(ctx.path):
            return
        cg = CallGraph(ctx.tree)
        fns = list(iter_functions(ctx.tree))

        # serving-reachable set for the ray.get arm: roots + depth-2 callees
        reach: set[int] = set()
        for fn, cls, _qual in fns:
            if not _is_root(fn.name):
                continue
            frontier = [(fn, cls, 2)]
            while frontier:
                f, c, d = frontier.pop()
                if id(f) in reach:
                    continue
                reach.add(id(f))
                if d == 0:
                    continue
                for n in _walk_body(f):
                    if isinstance(n, ast.Call):
                        callee = cg.resolve(n, c)
                        if callee is not None:
                            frontier.append((callee, cg.class_of(callee), d - 1))

        # forwarding helpers: fn -> {param: transport label}
        forwards: dict[int, tuple[object, dict[str, str]]] = {}
        for fn, _cls, _qual in fns:
            fwd = _forwarded_none_params(fn)
            if fwd:
                forwards[id(fn)] = (fn, fwd)

        seen: set[int] = set()
        for fn, cls, qual in fns:
            for node in _walk_body(fn):
                if not isinstance(node, ast.Call) or id(node) in seen:
                    continue
                msg = self._classify(node, cls, cg, forwards, id(fn) in reach)
                if msg is not None:
                    seen.add(id(node))
                    yield self.finding(ctx, node, msg, context=qual)

    @staticmethod
    def _classify(node: ast.Call, cls, cg: CallGraph, forwards, on_serving_root) -> str | None:
        fname = dotted(node.func) or ""
        last = fname.split(".")[-1]
        # explicit timeout=None on any transport shape is always a hazard
        kw = call_keyword(node, "timeout", "timeout_s")
        explicit_none = kw is not None and _is_none(kw.value)
        if isinstance(node.func, ast.Name) and node.func.id == "index_call":
            if explicit_none:
                return "index_call(timeout_s=None): an index RPC must keep its bounded default"
            return None
        hit = blocking_ray_call(node)
        if hit is not None:
            name, bounded = hit
            if explicit_none:
                return f"{name}(timeout=None) on a serving path blocks forever on a lost object"
            if not bounded and on_serving_root:
                return (
                    f"unbounded {name}() reachable from a serving root: pass timeout= "
                    "so a lost object surfaces as GetTimeoutError, not a hang"
                )
            return None
        # interprocedural: calling a local forwarding helper without its
        # timeout param leaves the transport call inside unbounded
        callee = cg.resolve(node, cls)
        if callee is not None and id(callee) in forwards:
            fn_def, fwd = forwards[id(callee)]
            for param, label in sorted(fwd.items()):
                if not _passes_param(node, fn_def, param):
                    return (
                        f"{callee.name}() called without {param}= — it forwards that "
                        f"None default into {label}, which then never times out"
                    )
        if not isinstance(node.func, ast.Attribute):
            return None
        attr = node.func.attr
        recv = dotted(node.func.value) or ""
        rlast = recv.split(".")[-1].lower() if recv else ""
        if attr == "get_owned_view":
            state, expr = _timeout_value(node, positional_idx=1)
            if state == "absent" or _is_none(expr):
                return (
                    f"{recv or '<plane>'}.get_owned_view() without a bounded timeout: "
                    "a lost owner parks the serving path forever"
                )
            return None
        if attr == "request" and ("conn" in rlast or "peer" in rlast):
            state, expr = _timeout_value(node, positional_idx=None)
            if state == "absent" or _is_none(expr):
                return (
                    f"{recv}.request() without timeout=: a dead peer never answers — "
                    "bound it so the caller fails over"
                )
            return None
        if explicit_none and (attr in _PLANE_FETCH_ATTRS or last == "fetch"):
            return f"{recv or fname}.{attr}(timeout=None) disables the transport's bounded default"
        return None


FAULT_RULES = (
    SwallowedException,
    NonTaxonomyRaise,
    RaiseWithoutCause,
    UnboundedRetryLoop,
    UnboundedTransportCall,
)


def all_fault_rules(select: set[str] | None = None) -> list[Rule]:
    from ray_tpu.lint.engine import canonical_rule

    rules = [cls() for cls in FAULT_RULES]
    if select:
        canon = {canonical_rule(s) for s in select}
        rules = [r for r in rules if r.id in canon or r.name in select]
    return rules


def fault_rule_catalog() -> list[tuple[str, str, str]]:
    return [(cls.id, cls.name, cls.summary) for cls in FAULT_RULES]


def fault_rule_ids() -> set[str]:
    return {cls.id for cls in FAULT_RULES}
