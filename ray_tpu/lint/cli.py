"""``python -m ray_tpu.lint`` — check, update-baseline, list-rules.

Exit codes: 0 clean (or baseline-covered), 1 new OR stale findings
(stale = an accepted entry no longer fully reproduces; it must be
re-accepted or its unused budget silently absorbs a reintroduction),
2 usage error. Also reachable as ``python -m ray_tpu.scripts.cli lint``.

``--jax`` adds the jaxpr-level pass (ray_tpu/lint/jaxcheck/): registered
entry points are imported and traced abstractly, and JXC findings merge
into the same baseline/suppression stream as the AST rules.

``--format=json`` emits ONE finding per line (JSON Lines: rule, path,
line, col, fingerprint, message, context) so CI and editors can consume
findings without parsing the human format; stale baseline entries follow
as lines with ``"stale": true``.

Baseline entries are judged only when this run could have re-found them:
an entry whose file is outside the linted paths, or whose rule was
deselected (JXC rules count as deselected when --jax is off), is neither
consulted for suppression nor reported stale — so ``--select``/subset
runs never produce phantom staleness, and ``--update-baseline`` on a
subset MERGES (entries outside the run's coverage are kept verbatim,
never silently deleted).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from ray_tpu.lint import baseline as baseline_mod
from ray_tpu.lint.engine import canonical_rule, lint_paths
from ray_tpu.lint.rules import all_rules, rule_catalog


def _coverage(paths: list[str], root: str, rule_ids: set[str]):
    """(rule, path) -> bool: could this run have re-found it? Rules are
    compared canonically, so a baseline entry keyed under a retired alias
    id (TPL004) is covered whenever its successor (CCR006) ran."""
    rel_roots = []
    for p in paths:
        rel = os.path.relpath(os.path.abspath(p), root).replace(os.sep, "/")
        rel_roots.append("" if rel == "." else rel)
    canon_ids = {canonical_rule(r) for r in rule_ids}

    def covered(rule: str, path: str) -> bool:
        if canonical_rule(rule) not in canon_ids:
            return False
        return any(r == "" or path == r or path.startswith(r + "/") for r in rel_roots)

    return covered


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m ray_tpu.lint",
        description="tpulint: AST + jaxpr static analyzer for distributed-runtime & TPU hazards",
    )
    p.add_argument("paths", nargs="*", default=["ray_tpu"], help="files/dirs to lint (default: ray_tpu)")
    p.add_argument("--root", default=None, help="path fingerprints are stored relative to (default: cwd)")
    p.add_argument("--baseline", default=None, help="baseline JSON (default: ray_tpu/lint/baseline.json)")
    p.add_argument("--no-baseline", action="store_true", help="report every finding; ignore the baseline")
    p.add_argument("--update-baseline", action="store_true", help="accept current findings into the baseline and exit 0")
    p.add_argument("--select", default=None, help="comma-separated rule ids/names to run (default: all; alias ids like TPL004 resolve)")
    p.add_argument("--concur", action="store_true", help="run only the CCR concurrency-discipline rules")
    p.add_argument("--fault", action="store_true", help="run only the ERR fault-discipline rules")
    p.add_argument("--jax", action="store_true", help="also trace registered entry points and run the JXC jaxpr rules")
    p.add_argument("--format", choices=("text", "json"), default="text",
                   help="json = one finding per line (JSON Lines)")
    p.add_argument("--list-rules", action="store_true")
    p.add_argument("--stats", action="store_true", help="print per-rule totals")
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        # all four catalogs, uniformly: TPL+CCR+ERR (rule_catalog spans
        # the merged AST registry) and JXC
        from ray_tpu.lint.jaxcheck import jax_rule_catalog

        for rid, name, summary in rule_catalog() + jax_rule_catalog():
            print(f"{rid}  {name:34s} {summary}")
        return 0

    select = {s.strip() for s in args.select.split(",") if s.strip()} if args.select else None
    if args.concur:
        from ray_tpu.lint.concur import concur_rule_ids

        select = (select or set()) | concur_rule_ids() if select else concur_rule_ids()
    if args.fault:
        from ray_tpu.lint.fault import fault_rule_ids

        select = (select or set()) | fault_rule_ids() if select else fault_rule_ids()
    rules = all_rules(select)
    root = os.path.abspath(args.root or os.getcwd())

    jax_rules: list = []
    if args.jax:
        from ray_tpu.lint.jaxcheck.rules import all_jax_rules

        jax_rules = all_jax_rules(select)
    if select and not rules and not jax_rules:
        print(f"no rules match --select {args.select}", file=sys.stderr)
        return 2
    try:
        if rules:
            findings = lint_paths(args.paths, root=root, rules=rules)
        else:
            # jax-only --select: skip the (pointless) full-tree parse but
            # keep the typo'd-path usage error the parse would have raised
            from ray_tpu.lint.engine import iter_py_files

            list(iter_py_files(args.paths))  # walks dirs only, reads no files
            findings = []
    except FileNotFoundError as e:
        print(f"tpulint: {e}", file=sys.stderr)
        return 2

    if args.jax and jax_rules:
        from ray_tpu.lint.jaxcheck import registry, run_jaxcheck

        jax_findings = run_jaxcheck(root=root, select=select)
        n_entries = len(registry.all_entries())
        print(f"tpulint: jaxcheck traced {n_entries} entry point(s)", file=sys.stderr)
        # subset runs keep subset semantics: a jax finding outside the
        # linted paths is invisible, exactly like an AST finding would be
        path_cov = _coverage(args.paths, root, {r.id for r in jax_rules} | {"JXCERR"})
        findings = sorted(
            findings + [f for f in jax_findings if path_cov(f.rule, f.path)],
            key=lambda f: (f.path, f.line, f.col, f.rule),
        )

    # JXCERR is "covered" only when the jax pass actually ran (it always
    # emits trace failures regardless of --select); otherwise a baseline
    # JXCERR entry would go phantom-stale on --jax --select TPL00x runs
    rule_ids = {r.id for r in rules} | {r.id for r in jax_rules} | ({"JXCERR"} if (args.jax and jax_rules) else set())
    covered = _coverage(args.paths, root, rule_ids)

    bl_path = args.baseline or baseline_mod.default_baseline_path()
    if args.update_baseline:
        prior = baseline_mod.load(bl_path)
        kept = {fp: e for fp, e in prior.items() if not covered(e.get("rule"), e.get("path", ""))}
        merged = {**kept, **baseline_mod.entries_from_findings(findings, prior=prior)}
        n = baseline_mod.save_entries(bl_path, merged)
        print(
            f"tpulint: wrote {n} baseline entries ({len(findings)} findings, "
            f"{len(kept)} kept from outside this run's coverage) to {bl_path}"
        )
        return 0

    entries = {} if args.no_baseline else baseline_mod.load(bl_path)
    entries = {fp: e for fp, e in entries.items() if covered(e.get("rule"), e.get("path", ""))}
    d = baseline_mod.diff(findings, entries)

    if args.format == "json":
        for f in d.new:
            print(json.dumps({
                "rule": f.rule, "path": f.path, "line": f.line, "col": f.col,
                "fingerprint": f.fingerprint(), "message": f.message, "context": f.context,
            }, sort_keys=False))
        for e in d.stale:
            print(json.dumps({
                "stale": True, "rule": e.get("rule"), "path": e.get("path"),
                "fingerprint": e.get("fingerprint"), "unused": e.get("unused"),
            }, sort_keys=False))
    else:
        for f in d.new:
            print(f.render())
        if args.stats:
            per_rule: dict[str, int] = {}
            for f in findings:
                per_rule[f.rule] = per_rule.get(f.rule, 0) + 1
            for rid in sorted(per_rule):
                print(f"  {rid}: {per_rule[rid]} total")
        for e in d.stale:
            print(
                f"tpulint: stale baseline entry {e['fingerprint']} "
                f"({e['rule']} {e['path']} [{e.get('context', '')}], unused budget "
                f"{e.get('unused', '?')}) — fixed? re-run with --update-baseline to drop it",
                file=sys.stderr,
            )
    tail = f"{len(d.new)} new finding(s), {d.suppressed} baseline-suppressed, {len(d.stale)} stale"
    print(f"tpulint: {tail}", file=sys.stderr)
    # stale fails too: unused budget left in place would silently absorb
    # the next reintroduction of the same finding
    return 1 if (d.new or d.stale) else 0


if __name__ == "__main__":
    sys.exit(main())
