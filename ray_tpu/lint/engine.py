"""Visitor core: file walking, per-file rule dispatch, findings,
fingerprints, inline suppression.

Design notes:

- One ``ast.parse`` per file; every rule gets the same tree via a
  ``FileContext``. Rules are independent visitors (the codebase is
  ~32 KLoC — clarity beats a fused single-pass dispatcher).
- Fingerprints deliberately EXCLUDE line/col: a baseline must survive
  unrelated edits above a finding. Identity is
  (rule, path, enclosing scope, message); multiple identical findings in
  one scope are disambiguated by count, not index, so reordering inside
  a function never churns the baseline.
- ``# tpulint: disable=CCR001`` (or ``=all``) on the flagged line
  suppresses in-source, for hazards that are deliberate and locally
  explainable; the baseline is for accepted pre-existing debt instead.
  Retired ids listed in ``RULE_ALIASES`` (``TPL004`` -> ``CCR006``)
  still suppress their successor rule.
"""

from __future__ import annotations

import ast
import hashlib
import os
import re
from dataclasses import dataclass, field
from typing import Iterable, Iterator

_SUPPRESS_RE = re.compile(r"#\s*tpulint:\s*disable=([A-Za-z0-9_,\s]+|all)")

# Retired rule ids that live on as aliases of their successor: old inline
# disables, --select args, and baseline entries keep working verbatim.
# TPL004 (lock-order cycles) moved into the concur catalog as CCR006;
# TPL007 (swallowed connection errors) generalized into the fault
# catalog as ERR001.
RULE_ALIASES = {"TPL004": "CCR006", "TPL007": "ERR001"}


def canonical_rule(rule_id: str) -> str:
    """Map a (possibly retired) rule id to its canonical catalog id."""
    return RULE_ALIASES.get(rule_id, rule_id)


@dataclass(frozen=True)
class Finding:
    rule: str  # "TPL001"
    path: str  # root-relative posix path
    line: int
    col: int
    message: str
    context: str = ""  # enclosing def/class qualname ("" = module level)

    def fingerprint(self) -> str:
        key = f"{self.rule}|{self.path}|{self.context}|{self.message}"
        return hashlib.sha1(key.encode()).hexdigest()[:16]

    def render(self) -> str:
        where = f" [{self.context}]" if self.context else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}{where}"


@dataclass
class FileContext:
    path: str  # root-relative posix path
    tree: ast.Module
    source: str
    lines: list[str] = field(default_factory=list)

    def __post_init__(self):
        if not self.lines:
            self.lines = self.source.splitlines()


class Rule:
    """One checker. Subclasses set ``id``/``name``/``summary`` and yield
    Findings from ``check``; ``finding()`` stamps the rule id and path."""

    id = "TPL000"
    name = "abstract"
    summary = ""

    def check(self, ctx: FileContext) -> Iterator[Finding]:  # pragma: no cover
        raise NotImplementedError

    def finding(self, ctx: FileContext, node: ast.AST, message: str, context: str = "") -> Finding:
        return Finding(
            rule=self.id,
            path=ctx.path,
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0),
            message=message,
            context=context,
        )


# ---------------------------------------------------------------------------
# shared AST helpers (every rule needs these; keep them in one place)
# ---------------------------------------------------------------------------
def dotted(expr: ast.AST) -> str | None:
    """'a.b.c' for a Name/Attribute chain; None for anything else."""
    parts: list[str] = []
    while isinstance(expr, ast.Attribute):
        parts.append(expr.attr)
        expr = expr.value
    if isinstance(expr, ast.Name):
        parts.append(expr.id)
        return ".".join(reversed(parts))
    return None


def decorator_names(node: ast.FunctionDef | ast.AsyncFunctionDef | ast.ClassDef) -> list[str]:
    """Dotted names of decorators, with call wrappers unwrapped:
    ``@ray.remote(num_cpus=1)`` -> 'ray.remote'. For ``partial(...)``
    decorators the partial'd callable's name is appended too, so
    ``@partial(jax.jit, static_argnums=0)`` yields both
    'functools.partial' and 'jax.jit'."""
    out: list[str] = []
    for dec in node.decorator_list:
        target = dec
        if isinstance(target, ast.Call):
            inner = dotted(target.func)
            if inner is not None:
                out.append(inner)
                if inner.split(".")[-1] == "partial" and target.args:
                    arg0 = dotted(target.args[0])
                    if arg0 is not None:
                        out.append(arg0)
            continue
        name = dotted(target)
        if name is not None:
            out.append(name)
    return out


def has_decorator(node, suffixes: tuple[str, ...]) -> bool:
    """True when any decorator's dotted name ends with one of ``suffixes``
    (last segment match: 'remote' hits ray.remote / ray_tpu.remote /
    bare remote)."""
    return any(d.split(".")[-1] in suffixes for d in decorator_names(node))


def call_keyword(call: ast.Call, *names: str) -> ast.keyword | None:
    for kw in call.keywords:
        if kw.arg in names:
            return kw
    return None


class ScopedVisitor(ast.NodeVisitor):
    """NodeVisitor that maintains a qualname scope stack. Subclasses call
    ``self.qualname`` for finding context and may override
    ``enter_scope``/``leave_scope`` hooks."""

    def __init__(self):
        self._scope: list[str] = []

    @property
    def qualname(self) -> str:
        return ".".join(self._scope)

    def _scoped(self, node):
        self._scope.append(node.name)
        try:
            self.enter_scope(node)
            self.generic_visit(node)
        finally:
            self.leave_scope(node)
            self._scope.pop()

    def enter_scope(self, node):  # hook
        pass

    def leave_scope(self, node):  # hook
        pass

    visit_FunctionDef = _scoped
    visit_AsyncFunctionDef = _scoped
    visit_ClassDef = _scoped


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------
def finding_suppressed(lines: list[str], f: Finding) -> bool:
    """Inline ``# tpulint: disable=`` check against the flagged line.
    Shared by the AST pass (via FileContext) and jaxcheck (which reads
    the entry's source file itself)."""
    if not (1 <= f.line <= len(lines)):
        return False
    m = _SUPPRESS_RE.search(lines[f.line - 1])
    if m is None:
        return False
    spec = m.group(1)
    if spec.strip() == "all":
        return True
    ids = {canonical_rule(s.strip()) for s in spec.split(",")}
    return canonical_rule(f.rule) in ids


def _suppressed(ctx: FileContext, f: Finding) -> bool:
    return finding_suppressed(ctx.lines, f)


def lint_source(source: str, path: str = "<string>", rules: Iterable[Rule] | None = None) -> list[Finding]:
    """Lint one source string (the unit-test entry point)."""
    from ray_tpu.lint.rules import all_rules

    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding("TPLERR", path, e.lineno or 0, e.offset or 0, f"syntax error: {e.msg}")]
    ctx = FileContext(path=path, tree=tree, source=source)
    out: list[Finding] = []
    for rule in rules if rules is not None else all_rules():
        for f in rule.check(ctx):
            if not _suppressed(ctx, f):
                out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def iter_py_files(paths: Iterable[str]) -> Iterator[str]:
    # overlapping path args (a tree and a file inside it) must not lint a
    # file twice: duplicate findings would overflow the baseline's
    # count-based suppression and fail a clean tree
    seen: set[str] = set()

    def once(fp: str) -> bool:
        ap = os.path.abspath(fp)
        if ap in seen:
            return False
        seen.add(ap)
        return True

    for p in paths:
        if os.path.isfile(p):
            if once(p):
                yield p
        elif os.path.isdir(p):
            for base, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs if d not in ("__pycache__", ".git"))
                for fn in sorted(files):
                    if fn.endswith(".py") and once(os.path.join(base, fn)):
                        yield os.path.join(base, fn)
        else:
            # a typo'd path (or wrong cwd for the relative default) must
            # not turn into a silently-green zero-file "clean" run
            raise FileNotFoundError(f"lint path does not exist: {p}")


def lint_paths(paths: Iterable[str], root: str | None = None, rules: Iterable[Rule] | None = None) -> list[Finding]:
    """Lint files/trees. Finding paths are stored relative to ``root``
    (default cwd) in posix form so fingerprints are machine-independent."""
    root = os.path.abspath(root or os.getcwd())
    rules = list(rules) if rules is not None else None
    out: list[Finding] = []
    for fp in iter_py_files(paths):
        try:
            with open(fp, encoding="utf-8", errors="replace") as fh:
                src = fh.read()
        except OSError:
            continue
        rel = os.path.relpath(os.path.abspath(fp), root).replace(os.sep, "/")
        out.extend(lint_source(src, path=rel, rules=rules))
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out
