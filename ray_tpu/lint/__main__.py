import sys

from ray_tpu.lint.cli import main

sys.exit(main())
