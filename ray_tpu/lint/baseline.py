"""Baseline suppression: accepted pre-existing findings, keyed by
line-independent fingerprint.

Format (JSON, sorted, diff-friendly):

    {
      "version": 1,
      "tool": "tpulint",
      "entries": {
        "<sha1[:16]>": {"rule": "CCR001", "path": "ray_tpu/core/x.py",
                         "context": "Cls.meth", "message": "...", "count": 2,
                         "why": "deliberate: <justification>"}
      }
    }

``count`` is how many identical (rule, path, context, message) findings
are accepted: a new duplicate of an accepted finding still fails the
check. ``why`` is the hand-written justification for accepting the
hazard — required by policy for every entry, preserved verbatim across
``--update-baseline`` runs. Fingerprints exclude line numbers, so edits
elsewhere in a file never churn the baseline; a stale entry (finding
fixed — fully or just part of its accepted count) is reported so the
baseline shrinks over time instead of fossilizing into silent headroom
for reintroductions. Entries keyed under a retired alias id (TPL004 ->
CCR006) keep suppressing their finding under the successor id.
"""

from __future__ import annotations

import json
import os
from collections import Counter
from dataclasses import dataclass, field, replace

from ray_tpu.lint.engine import Finding, RULE_ALIASES

# canonical rule id -> retired alias ids whose fingerprints still count:
# a baseline accepted under TPL004 keeps suppressing the same finding now
# reported as CCR006, so absorbing a rule never churns committed baselines
_ALIASES_OF: dict[str, list[str]] = {}
for _old, _new in RULE_ALIASES.items():
    _ALIASES_OF.setdefault(_new, []).append(_old)


def candidate_fingerprints(f: Finding) -> list[str]:
    """The finding's own fingerprint, then fingerprints it would have had
    under any retired alias id of its rule."""
    return [f.fingerprint()] + [
        replace(f, rule=old).fingerprint() for old in _ALIASES_OF.get(f.rule, ())
    ]


@dataclass
class BaselineDiff:
    new: list[Finding] = field(default_factory=list)
    suppressed: int = 0
    stale: list[dict] = field(default_factory=list)  # baseline entries no longer found


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "baseline.json")


def load(path: str) -> dict[str, dict]:
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    return dict(doc.get("entries", {}))


def entries_from_findings(findings: list[Finding], prior: dict[str, dict] | None = None) -> dict[str, dict]:
    """Baseline entries for ``findings``. When ``prior`` entries are
    given, hand-written ``why`` justifications are carried over (matched
    by fingerprint, alias fingerprints included) so ``--update-baseline``
    never silently discards the documented reason an entry exists."""
    counts: Counter[str] = Counter(f.fingerprint() for f in findings)
    entries: dict[str, dict] = {}
    for f in findings:
        fp = f.fingerprint()
        if fp not in entries:
            entries[fp] = {
                "rule": f.rule,
                "path": f.path,
                "context": f.context,
                "message": f.message,
                "count": counts[fp],
            }
            if prior:
                for cand in candidate_fingerprints(f):
                    why = prior.get(cand, {}).get("why")
                    if why is not None:
                        entries[fp]["why"] = why
                        break
    return entries


def save_entries(path: str, entries: dict[str, dict]) -> int:
    doc = {"version": 1, "tool": "tpulint", "entries": dict(sorted(entries.items()))}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1, sort_keys=False)
        fh.write("\n")
    return len(entries)


def save(path: str, findings: list[Finding]) -> int:
    return save_entries(path, entries_from_findings(findings))


def diff(findings: list[Finding], entries: dict[str, dict]) -> BaselineDiff:
    out = BaselineDiff()
    budget = {fp: int(e.get("count", 1)) for fp, e in entries.items()}
    used: Counter[str] = Counter()
    for f in findings:
        for fp in candidate_fingerprints(f):
            if budget.get(fp, 0) > 0:
                budget[fp] -= 1
                used[fp] += 1
                out.suppressed += 1
                break
        else:
            out.new.append(f)
    # stale includes PARTIALLY-fixed entries: leaving an unused budget of
    # n would let n future reintroductions of the same finding slide
    # through the gate silently — force an --update-baseline instead
    out.stale = [
        dict(entries[fp], fingerprint=fp, unused=int(entries[fp].get("count", 1)) - used[fp])
        for fp in sorted(entries)
        if used[fp] < int(entries[fp].get("count", 1))
    ]
    return out
