"""Baseline suppression: accepted pre-existing findings, keyed by
line-independent fingerprint.

Format (JSON, sorted, diff-friendly):

    {
      "version": 1,
      "tool": "tpulint",
      "entries": {
        "<sha1[:16]>": {"rule": "TPL004", "path": "ray_tpu/core/x.py",
                         "context": "Cls.meth", "message": "...", "count": 2}
      }
    }

``count`` is how many identical (rule, path, context, message) findings
are accepted: a new duplicate of an accepted finding still fails the
check. Fingerprints exclude line numbers, so edits elsewhere in a file
never churn the baseline; a stale entry (finding fixed — fully or just
part of its accepted count) is reported so the baseline shrinks over
time instead of fossilizing into silent headroom for reintroductions.
"""

from __future__ import annotations

import json
import os
from collections import Counter
from dataclasses import dataclass, field

from ray_tpu.lint.engine import Finding


@dataclass
class BaselineDiff:
    new: list[Finding] = field(default_factory=list)
    suppressed: int = 0
    stale: list[dict] = field(default_factory=list)  # baseline entries no longer found


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "baseline.json")


def load(path: str) -> dict[str, dict]:
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    return dict(doc.get("entries", {}))


def entries_from_findings(findings: list[Finding]) -> dict[str, dict]:
    counts: Counter[str] = Counter(f.fingerprint() for f in findings)
    entries: dict[str, dict] = {}
    for f in findings:
        fp = f.fingerprint()
        if fp not in entries:
            entries[fp] = {
                "rule": f.rule,
                "path": f.path,
                "context": f.context,
                "message": f.message,
                "count": counts[fp],
            }
    return entries


def save_entries(path: str, entries: dict[str, dict]) -> int:
    doc = {"version": 1, "tool": "tpulint", "entries": dict(sorted(entries.items()))}
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, indent=1, sort_keys=False)
        fh.write("\n")
    return len(entries)


def save(path: str, findings: list[Finding]) -> int:
    return save_entries(path, entries_from_findings(findings))


def diff(findings: list[Finding], entries: dict[str, dict]) -> BaselineDiff:
    out = BaselineDiff()
    budget = {fp: int(e.get("count", 1)) for fp, e in entries.items()}
    used: Counter[str] = Counter()
    for f in findings:
        fp = f.fingerprint()
        if budget.get(fp, 0) > 0:
            budget[fp] -= 1
            used[fp] += 1
            out.suppressed += 1
        else:
            out.new.append(f)
    # stale includes PARTIALLY-fixed entries: leaving an unused budget of
    # n would let n future reintroductions of the same finding slide
    # through the gate silently — force an --update-baseline instead
    out.stale = [
        dict(entries[fp], fingerprint=fp, unused=int(entries[fp].get("count", 1)) - used[fp])
        for fp in sorted(entries)
        if used[fp] < int(entries[fp].get("count", 1))
    ]
    return out
