"""Module-level call graph + blocking-call classifier for
interprocedural AST rules.

Resolution covers the two intra-module call shapes that matter:
bare ``helper(...)`` to a module-level def, and ``self.meth(...)`` to a
method of the enclosing class. ``blocking_effects`` summarizes what a
callee (transitively, to ``depth`` further resolutions — default 2)
can block on, so a rule holding a lock-set at a call site can apply the
classifier THROUGH local helpers without building a whole-program
analysis whose approximations would drown the signal.

The classifier (``classify_blocking`` / ``classify_device_sync``) is the
single definition of "a call that can stall the caller" for the concur
(CCR) rules, and it is deliberately domain-aware: beyond the generic
shapes (``time.sleep``, thread ``.join()``, zero-arg ``.get()``/
``.wait()`` without a timeout, ``ray.get``/``ray.wait``) it names this
codebase's planes — direct-plane owned-object traffic
(``put_owned``/``get_owned_view``/``free_owned``), index RPCs on
plane/index/client receivers (``lookup``/``fetch``/``publish``/
``register``/...), engine-lock entry points on engine receivers
(``step``/``host_load``/the stats reads), and the device-sync shapes
that force a host readback (``np.asarray``, ``jax.device_get``,
``.item()``, ``.block_until_ready()``, ``float(x[i])``).
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, replace

from ray_tpu.lint.engine import call_keyword, dotted

# The single definition of "a blocking runtime call" — blocking_get.py
# (lexical pass) and the interprocedural helpers below both consume
# these, so the two passes cannot drift apart.
BLOCKING_ATTRS = {"get", "wait"}
BLOCKING_MODULES = {"ray", "ray_tpu", "rt"}

# receivers that look like a KV-plane client / cluster index handle — an
# attribute call on one of these is (or proxies) an RPC with a timeout,
# never plain dict work
_PLANE_RECV = re.compile(r"(plane|index|client|idx)$", re.IGNORECASE)
_PLANE_ATTRS = {
    "lookup", "fetch", "publish", "register", "unregister", "heartbeat",
    "drop_replica", "report_lost", "match_replicas", "shutdown", "expire",
}
# direct-plane owned-object traffic blocks on transport regardless of
# receiver spelling
_DIRECT_PLANE_ATTRS = {"put_owned", "get_owned_view", "free_owned"}
# engine entry points that acquire the ENGINE lock (held for whole
# serving steps — seconds of prefill): calling one while holding another
# lock nests lock waits invisibly to the lexical cycle rule
_ENGINE_RECV = re.compile(r"(^|_)eng(ine)?$", re.IGNORECASE)
_ENGINE_ATTRS = {"step", "host_load", "kv_cache_stats", "spec_stats", "prefix_cache_stats"}


def blocking_ray_call(node: ast.Call) -> tuple[str, bool] | None:
    """(dotted name, bounded?) when ``node`` is ``ray.get()``/``ray.wait()``
    style; None otherwise. ``bounded`` means a ``timeout=`` was passed."""
    name = dotted(node.func)
    if name is None:
        return None
    parts = name.split(".")
    if len(parts) == 2 and parts[0] in BLOCKING_MODULES and parts[1] in BLOCKING_ATTRS:
        return name, call_keyword(node, "timeout") is not None
    return None


@dataclass(frozen=True)
class Effect:
    """One way a call (or its transitive callees) can stall the caller.

    ``chain`` is the resolved intermediate callees between the call site
    a rule is looking at and the terminal blocking call (empty for a
    direct hit); ``node`` is the terminal call's AST node (its file is
    always the analyzed file — resolution never leaves the module);
    ``recv`` is the terminal call's dotted receiver ("" for bare calls),
    which CCR001 uses to exempt the condition-variable ``wait()``-on-
    the-held-lock pattern."""

    kind: str       # sleep | join | unbounded-get | unbounded-wait | ray-get |
                    # plane | index-rpc | engine-call | device-sync
    label: str      # human label, e.g. "self._kv_plane.lookup()"
    recv: str       # dotted receiver of the terminal call ("" if none)
    node: ast.Call
    chain: tuple[str, ...] = ()
    bounded: bool = False

    def describe(self) -> str:
        via = f" via {' -> '.join(self.chain)}" if self.chain else ""
        return f"{self.label} [{self.kind}]{via}"


def _is_thread_join(call: ast.Call) -> bool:
    """``x.join()`` / ``x.join(5.0)`` / ``x.join(timeout=...)`` — a
    thread-style join. ``sep.join(parts)`` (str.join) always passes a
    non-numeric positional iterable, so it never matches."""
    if len(call.args) > 1:
        return False
    if len(call.args) == 1:
        a = call.args[0]
        if not (isinstance(a, ast.Constant) and isinstance(a.value, (int, float))):
            return False
    return True


def classify_blocking(call: ast.Call) -> Effect | None:
    """Classify one call as a blocking shape (see module docstring), or
    None. Device syncs are classified separately (classify_device_sync):
    CCR001 (blocking under lock) and CCR002 (hot-path sync) own
    different halves of the taxonomy."""
    name = dotted(call.func)
    if name == "time.sleep":
        return Effect("sleep", "time.sleep()", "", call, bounded=True)
    hit = blocking_ray_call(call)
    if hit is not None:
        return Effect("ray-get", f"{hit[0]}()", name.split(".")[0], call, bounded=hit[1])
    if isinstance(call.func, ast.Name) and call.func.id == "index_call":
        return Effect("index-rpc", "index_call()", "", call, bounded=True)
    if not isinstance(call.func, ast.Attribute):
        return None
    attr = call.func.attr
    recv = dotted(call.func.value) or ""
    rlast = recv.split(".")[-1] if recv else ""
    label = f"{recv}.{attr}()" if recv else f".{attr}()"
    if attr in _DIRECT_PLANE_ATTRS:
        return Effect("plane", label, recv, call)
    if attr == "join" and _is_thread_join(call):
        # string-literal receivers are str.join even with 0 args
        if isinstance(call.func.value, (ast.Constant, ast.JoinedStr)):
            return None
        return Effect("join", label, recv, call, bounded=bool(call.args or call.keywords))
    if attr in ("get", "wait") and not call.args and call_keyword(call, "timeout") is None:
        # zero-arg get/wait with no timeout: queue.get()/event.wait()
        # block forever (dict.get/os.wait shapes all take positionals)
        return Effect(f"unbounded-{attr}", label, recv, call)
    if rlast and _PLANE_RECV.search(rlast) and attr in _PLANE_ATTRS:
        return Effect("index-rpc", label, recv, call, bounded=True)
    if rlast and _ENGINE_RECV.search(rlast) and attr in _ENGINE_ATTRS:
        return Effect("engine-call", label, recv, call)
    return None


def classify_device_sync(call: ast.Call) -> Effect | None:
    """Device-to-host sync shapes: the calls that force the host to wait
    for device work (and pull bytes over PCIe/ICI). ``float(x[i])``
    matches only a SUBSCRIPT argument — the scalar-readback idiom —
    because ``float(name)`` over host state is everywhere and benign."""
    name = dotted(call.func)
    if name is not None:
        parts = name.split(".")
        if parts[0] in ("np", "numpy") and parts[-1] in ("asarray", "array"):
            return Effect("device-sync", f"{name}()", "", call)
        if name == "jax.device_get":
            return Effect("device-sync", "jax.device_get()", "", call)
    if isinstance(call.func, ast.Name) and call.func.id == "float":
        if len(call.args) == 1 and isinstance(call.args[0], ast.Subscript):
            sl = call.args[0].slice
            # string-keyed subscripts are host dict lookups, not lanes
            if not (isinstance(sl, ast.Constant) and isinstance(sl.value, str)):
                return Effect("device-sync", "float(<subscript>)", "", call)
    if isinstance(call.func, ast.Attribute):
        recv = dotted(call.func.value) or ""
        if call.func.attr == "item" and not call.args:
            return Effect("device-sync", f"{recv}.item()" if recv else ".item()", recv, call)
        if call.func.attr == "block_until_ready":
            return Effect(
                "device-sync", f"{recv}.block_until_ready()" if recv else ".block_until_ready()", recv, call
            )
    return None


class CallGraph:
    """Resolves intra-module calls (module-level defs and same-class
    methods) and answers the per-callee questions the interprocedural
    rules ask."""

    def __init__(self, tree: ast.Module):
        self.module_fns: dict[str, ast.FunctionDef | ast.AsyncFunctionDef] = {}
        self.methods: dict[tuple[str, str], ast.FunctionDef | ast.AsyncFunctionDef] = {}
        self.owner_class: dict[int, str] = {}  # id(def node) -> class name
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.module_fns[node.name] = node
            elif isinstance(node, ast.ClassDef):
                for sub in node.body:
                    if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                        self.methods[(node.name, sub.name)] = sub
                        self.owner_class[id(sub)] = node.name
        self._effects_memo: dict[tuple[int, int], tuple[Effect, ...]] = {}

    def resolve(
        self, call: ast.Call, cls: str | None = None
    ) -> ast.FunctionDef | ast.AsyncFunctionDef | None:
        """``helper(...)`` -> the module-level def; ``self.meth(...)``
        (given the enclosing class) -> the method def; else None.
        Foreign-object attribute calls (``mod.f()``, ``handle.x()``) stay
        unresolved — classify_blocking names the ones that matter."""
        if isinstance(call.func, ast.Name):
            return self.module_fns.get(call.func.id)
        if (
            cls is not None
            and isinstance(call.func, ast.Attribute)
            and isinstance(call.func.value, ast.Name)
            and call.func.value.id == "self"
        ):
            return self.methods.get((cls, call.func.attr))
        return None

    def class_of(self, fn: ast.FunctionDef | ast.AsyncFunctionDef) -> str | None:
        return self.owner_class.get(id(fn))

    def blocking_effects(
        self, fn: ast.FunctionDef | ast.AsyncFunctionDef, depth: int = 2
    ) -> list[Effect]:
        """Every blocking/device-sync Effect reachable from ``fn``'s
        lexical body, following resolvable calls up to ``depth`` further
        levels (classification itself is free: a classified call at the
        deepest resolved body still reports). Memoized; cycle-safe (the
        depth budget bounds recursion)."""
        key = (id(fn), depth)
        memo = self._effects_memo.get(key)
        if memo is not None:
            return list(memo)
        self._effects_memo[key] = ()  # cut self-recursion while computing
        out: list[Effect] = []
        seen: set[tuple[str, str, tuple[str, ...], int]] = set()

        def add(eff: Effect) -> None:
            # per-SITE identity: two np.asarray sites in one callee are two
            # effects (each needs its own anchor for inline disables)
            k = (eff.kind, eff.label, eff.chain, id(eff.node))
            if k not in seen:
                seen.add(k)
                out.append(eff)

        cls = self.class_of(fn)
        for node in _walk_body(fn):
            if not isinstance(node, ast.Call):
                continue
            eff = classify_blocking(node) or classify_device_sync(node)
            if eff is not None:
                add(eff)
                continue
            if depth > 0:
                callee = self.resolve(node, cls)
                if callee is not None and callee is not fn:
                    for sub in self.blocking_effects(callee, depth - 1):
                        add(replace(sub, chain=(callee.name,) + sub.chain))
        self._effects_memo[key] = tuple(out)
        return out

    def blocking_calls(self, fn: ast.FunctionDef | ast.AsyncFunctionDef) -> list[tuple[ast.Call, str, bool]]:
        """(call node, dotted name, bounded?) for every ray.get()/
        ray.wait() in ``fn``'s lexical body (nested defs excluded —
        defining a closure executes nothing). Callers decide whether a
        ``timeout=`` bound clears the hazard: it does for actor-deadlock,
        it does NOT for an event loop, which a bounded get still parks."""
        out: list[tuple[ast.Call, str, bool]] = []
        for node in _walk_body(fn):
            if isinstance(node, ast.Call):
                hit = blocking_ray_call(node)
                if hit is not None:
                    out.append((node, hit[0], hit[1]))
        return out

    def returns_object_ref(self, fn: ast.FunctionDef | ast.AsyncFunctionDef) -> bool:
        """True when some ``return`` in ``fn``'s lexical body returns a
        ``.remote()`` call (directly or in a tuple) — the caller receives
        an ObjectRef it must not drop."""
        for node in _walk_body(fn):
            if isinstance(node, ast.Return) and node.value is not None:
                values = node.value.elts if isinstance(node.value, ast.Tuple) else [node.value]
                for v in values:
                    if (
                        isinstance(v, ast.Call)
                        and isinstance(v.func, ast.Attribute)
                        and v.func.attr == "remote"
                    ):
                        return True
        return False


def _walk_body(fn: ast.FunctionDef | ast.AsyncFunctionDef):
    """ast.walk over the function body, skipping nested function/class
    definitions (their bodies don't run when ``fn`` runs)."""
    stack: list[ast.AST] = list(fn.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))
