"""Module-level call graph for interprocedural AST rules.

One level deep, by design: rules that follow a call resolve it to a
definition in the SAME module (bare ``helper(...)`` to a module-level
def) and inspect that body lexically — they do not chase further calls.
That catches the dominant refactor pattern (hazard hoisted into a local
helper, invisible to a purely lexical rule) without building a whole-
program analysis whose approximations would drown the signal.
"""

from __future__ import annotations

import ast

from ray_tpu.lint.engine import call_keyword, dotted

# The single definition of "a blocking runtime call" — blocking_get.py
# (lexical pass) and the interprocedural helpers below both consume
# these, so the two passes cannot drift apart.
BLOCKING_ATTRS = {"get", "wait"}
BLOCKING_MODULES = {"ray", "ray_tpu", "rt"}


def blocking_ray_call(node: ast.Call) -> tuple[str, bool] | None:
    """(dotted name, bounded?) when ``node`` is ``ray.get()``/``ray.wait()``
    style; None otherwise. ``bounded`` means a ``timeout=`` was passed."""
    name = dotted(node.func)
    if name is None:
        return None
    parts = name.split(".")
    if len(parts) == 2 and parts[0] in BLOCKING_MODULES and parts[1] in BLOCKING_ATTRS:
        return name, call_keyword(node, "timeout") is not None
    return None


class CallGraph:
    """Resolves intra-module calls and answers the per-callee questions
    the interprocedural rules ask."""

    def __init__(self, tree: ast.Module):
        self.module_fns: dict[str, ast.FunctionDef | ast.AsyncFunctionDef] = {}
        for node in tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.module_fns[node.name] = node

    def resolve(self, call: ast.Call) -> ast.FunctionDef | ast.AsyncFunctionDef | None:
        """``helper(...)`` -> the module-level def, else None. Attribute
        calls (``self.x()``, ``mod.f()``) are out of scope: methods are
        already visited in their defining class's context, and foreign
        modules are other files."""
        if isinstance(call.func, ast.Name):
            return self.module_fns.get(call.func.id)
        return None

    def blocking_calls(self, fn: ast.FunctionDef | ast.AsyncFunctionDef) -> list[tuple[ast.Call, str, bool]]:
        """(call node, dotted name, bounded?) for every ray.get()/
        ray.wait() in ``fn``'s lexical body (nested defs excluded —
        defining a closure executes nothing). Callers decide whether a
        ``timeout=`` bound clears the hazard: it does for actor-deadlock,
        it does NOT for an event loop, which a bounded get still parks."""
        out: list[tuple[ast.Call, str, bool]] = []
        for node in _walk_body(fn):
            if isinstance(node, ast.Call):
                hit = blocking_ray_call(node)
                if hit is not None:
                    out.append((node, hit[0], hit[1]))
        return out

    def returns_object_ref(self, fn: ast.FunctionDef | ast.AsyncFunctionDef) -> bool:
        """True when some ``return`` in ``fn``'s lexical body returns a
        ``.remote()`` call (directly or in a tuple) — the caller receives
        an ObjectRef it must not drop."""
        for node in _walk_body(fn):
            if isinstance(node, ast.Return) and node.value is not None:
                values = node.value.elts if isinstance(node.value, ast.Tuple) else [node.value]
                for v in values:
                    if (
                        isinstance(v, ast.Call)
                        and isinstance(v.func, ast.Attribute)
                        and v.func.attr == "remote"
                    ):
                        return True
        return False


def _walk_body(fn: ast.FunctionDef | ast.AsyncFunctionDef):
    """ast.walk over the function body, skipping nested function/class
    definitions (their bodies don't run when ``fn`` runs)."""
    stack: list[ast.AST] = list(fn.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))
