"""CCR rules: concurrency discipline over the lock-set dataflow.

CCR001  blocking-under-lock        blocking call (classifier, applied
                                   transitively through local helpers)
                                   while a lock is held
CCR002  hot-path-device-sync       device sync reachable (depth 2) from
                                   an engine hot-path root
CCR003  guarded-by-violation       write to a ``# guarded-by:`` field
                                   without the named lock held
CCR004  acquire-without-release    manual ``.acquire()`` not covered by
                                   a ``try/finally`` release
CCR005  thread-unguarded-capture   ``threading.Thread`` target mutates
                                   captured state with no lock guard
CCR006  lock-order-cycle           lexical ABBA ordering cycle
                                   (absorbed TPL004; the old id stays a
                                   live alias for baselines/disables)

Deliberate hazards go to the baseline with a ``why`` (pre-existing debt,
e.g. the ROADMAP item-3a admission fetch) or an inline
``# tpulint: disable=CCR00x`` (locally explainable, e.g. the sanctioned
one-step-delayed drain readback).
"""

from __future__ import annotations

import ast
from dataclasses import replace
from typing import Iterator

from ray_tpu.lint.callgraph import CallGraph, classify_blocking, _walk_body
from ray_tpu.lint.engine import FileContext, Finding, Rule, call_keyword, dotted
from ray_tpu.lint.concur.lockset import (
    MUTATOR_ATTRS,
    acquire_key,
    guarded_fields,
    holds_locks,
    iter_functions,
    iter_held,
    lock_key,
    self_attr_root,
)


class BlockingUnderLock(Rule):
    id = "CCR001"
    name = "blocking-under-lock"
    summary = "blocking call (plane/index RPC, sleep, join, unbounded get/wait, engine entry) while a lock is held"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        cg = CallGraph(ctx.tree)
        for fn, cls, qual in iter_functions(ctx.tree):
            seed = holds_locks(ctx.lines, fn, cls)
            skip: set[int] = set()
            seen: set[tuple[int, str, str]] = set()
            for node, held in iter_held(fn, cls, seed):
                if not held or not isinstance(node, ast.Call) or id(node) in skip:
                    continue
                if isinstance(node.func, ast.Attribute):
                    recv_key = lock_key(node.func.value, cls)
                    if recv_key is not None and recv_key in held:
                        # a call ON a held lock: cv.wait()/notify() inside
                        # ``with cv:`` is the condition-variable protocol,
                        # release/locked are bookkeeping — not hazards
                        continue
                effects = []
                eff = classify_blocking(node)
                if eff is not None:
                    effects = [eff]
                else:
                    callee = cg.resolve(node, cls)
                    if callee is not None:
                        effects = [
                            replace(e, chain=(callee.name,) + e.chain)
                            for e in cg.blocking_effects(callee, depth=2)
                            if e.kind != "device-sync"  # CCR002's half of the taxonomy
                        ]
                if not effects:
                    continue
                locks = ", ".join(sorted(held))
                for e in effects:
                    key = (id(node), e.kind, e.label)
                    if key in seen:
                        continue
                    seen.add(key)
                    yield self.finding(
                        ctx, node, f"{e.describe()} while holding {locks}", context=qual
                    )
                # nested calls inside a reported anchor would re-report
                # the same hazard from a deeper (noisier) vantage point
                skip.update(id(n) for n in ast.walk(node) if isinstance(n, ast.Call))


def _hot_root(name: str) -> bool:
    """Engine hot-path roots: the per-step serving loop and the telemetry
    sample sites it calls. ``_drain_once`` (the cold shutdown drain in
    serve/) is NOT one — only exact ``_drain``/``_drain_spec`` (the
    device-readback tails of the fused step) qualify."""
    return (
        name in ("step", "on_step", "record_step", "_drain", "_drain_spec", "_sync_decode")
        or name.startswith("_stage_")
        or name.startswith("_dispatch")
    )


class HotPathDeviceSync(Rule):
    id = "CCR002"
    name = "hot-path-device-sync"
    summary = "device-to-host sync (np.asarray/.item()/float(x[i])/block_until_ready) reachable from an engine hot-path root"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        cg = CallGraph(ctx.tree)
        owner: dict[int, str] = {}
        roots = []
        for fn, cls, qual in iter_functions(ctx.tree):
            for n in _walk_body(fn):
                if isinstance(n, ast.Call):
                    owner.setdefault(id(n), qual)
            if _hot_root(fn.name):
                roots.append((fn, qual))
        reported: set[int] = set()
        for fn, qual in roots:
            for e in cg.blocking_effects(fn, depth=2):
                if e.kind != "device-sync" or id(e.node) in reported:
                    continue
                reported.add(id(e.node))
                via = f" via {' -> '.join(e.chain)}" if e.chain else ""
                yield self.finding(
                    ctx, e.node,
                    f"device sync {e.label} reachable from hot path {qual}(){via}",
                    context=owner.get(id(e.node), qual),
                )


def _name_root(expr: ast.AST) -> str | None:
    """The root Name id of an Attribute/Subscript chain, or None."""
    while isinstance(expr, (ast.Attribute, ast.Subscript)):
        expr = expr.value
    return expr.id if isinstance(expr, ast.Name) else None


class GuardedByViolation(Rule):
    id = "CCR003"
    name = "guarded-by-violation"
    summary = "write to a `# guarded-by: <lock>` field without the named lock in the lock-set"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        fields = guarded_fields(ctx.lines, ctx.tree)
        if not fields:
            return
        for fn, cls, qual in iter_functions(ctx.tree):
            if cls not in fields or fn.name == "__init__":
                continue
            decls = fields[cls]
            seed = holds_locks(ctx.lines, fn, cls)
            for node, held in iter_held(fn, cls, seed):
                for attr, write in self._writes(node):
                    need = decls.get(attr)
                    if need is not None and need not in held:
                        yield self.finding(
                            ctx, node,
                            f"{write} self.{attr} without holding {need} (declared `# guarded-by`)",
                            context=qual,
                        )

    @staticmethod
    def _writes(node: ast.AST) -> Iterator[tuple[str, str]]:
        """(guarded attr, verb) for every write this node performs."""
        if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign, ast.Delete)):
            targets = (
                node.targets if isinstance(node, (ast.Assign, ast.Delete)) else [node.target]
            )
            verb = "del of" if isinstance(node, ast.Delete) else "write to"
            for t in targets:
                for leaf in t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]:
                    attr = self_attr_root(leaf)
                    if attr is not None:
                        yield attr, verb
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            if node.func.attr in MUTATOR_ATTRS:
                attr = self_attr_root(node.func.value)
                if attr is not None:
                    yield attr, f".{node.func.attr}() on"


class AcquireWithoutRelease(Rule):
    id = "CCR004"
    name = "acquire-without-release"
    summary = "manual `.acquire()` whose release is not guaranteed by a try/finally"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for fn, cls, qual in iter_functions(ctx.tree):
            yield from self._block(fn.body, cls, qual, [], [], ctx)

    def _block(self, stmts, cls, qual, chain, tries, ctx) -> Iterator[Finding]:
        for i, stmt in enumerate(stmts):
            k = acquire_key(stmt, cls)
            if k is not None:
                recv = dotted(stmt.value.func.value)
                if not (
                    any(self._releases(t.finalbody, recv) for t in tries)
                    or self._released_after(chain + [(stmts, i)], recv)
                ):
                    yield self.finding(
                        ctx, stmt.value,
                        f"{recv}.acquire() is not followed by (or enclosed in) a "
                        f"try/finally that calls {recv}.release() — an exception "
                        "leaks the lock; prefer `with`",
                        context=qual,
                    )
                continue
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                continue  # own walk via iter_functions
            for blocks, sub_tries in self._child_blocks(stmt, tries):
                yield from self._block(blocks, cls, qual, chain + [(stmts, i)], sub_tries, ctx)

    @staticmethod
    def _child_blocks(stmt, tries):
        if isinstance(stmt, ast.Try):
            yield stmt.body, tries + [stmt]
            for h in stmt.handlers:
                yield h.body, tries
            yield stmt.orelse, tries
            yield stmt.finalbody, tries
            return
        for _, value in ast.iter_fields(stmt):
            if isinstance(value, list) and value and isinstance(value[0], ast.stmt):
                yield value, tries
            elif isinstance(value, list) and value and isinstance(value[0], ast.match_case):
                for case in value:
                    yield case.body, tries

    @classmethod
    def _released_after(cls, chain, recv) -> bool:
        """Is the statement AFTER the acquire (popping out of enclosing
        blocks when the acquire is a block's last statement — the
        hand-over-hand chained-locking shape) a try/finally releasing
        ``recv``?"""
        stmts, i = chain[-1]
        if i + 1 < len(stmts):
            nxt = stmts[i + 1]
            return isinstance(nxt, ast.Try) and cls._releases(nxt.finalbody, recv)
        if len(chain) > 1:
            return cls._released_after(chain[:-1], recv)
        return False

    @staticmethod
    def _releases(body, recv) -> bool:
        for stmt in body:
            for n in ast.walk(stmt):
                if (
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "release"
                    and dotted(n.func.value) == recv
                ):
                    return True
        return False


class ThreadUnguardedCapture(Rule):
    id = "CCR005"
    name = "thread-unguarded-capture"
    summary = "threading.Thread target mutates state captured from the spawning scope with no lock guard"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        for fn, cls, qual in iter_functions(ctx.tree):
            nested = {
                d.name: d
                for d, dcls, dq in iter_functions_within(fn)
            }
            outer_names = _assigned_names(fn)
            for node in _walk_body(fn):
                if not (isinstance(node, ast.Call) and _is_thread_ctor(node)):
                    continue
                tkw = call_keyword(node, "target")
                if tkw is None:
                    continue
                target = tkw.value
                if isinstance(target, ast.Name) and target.id in nested:
                    tfn = nested[target.id]
                    if holds_locks(ctx.lines, tfn, cls) or _has_lock_guard(tfn, cls):
                        continue
                    mutated = _mutated_captures(tfn, outer_names)
                    label = f"nested function {target.id}"
                elif isinstance(target, ast.Lambda):
                    mutated = _lambda_mutations(target, outer_names)
                    label = "lambda"
                else:
                    continue  # bound methods guard via their own class lock
                if mutated:
                    yield self.finding(
                        ctx, node,
                        f"Thread target {label} mutates captured state "
                        f"({', '.join(sorted(mutated))}) with no lock guard "
                        "(no `with <lock>:` in the target, no `# holds-lock:`)",
                        context=qual,
                    )


def iter_functions_within(fn: ast.FunctionDef | ast.AsyncFunctionDef):
    """Function defs nested directly under ``fn``'s lexical body (any
    block depth, but not inside a deeper def)."""

    def walk(stmts):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield stmt, None, stmt.name
                continue
            for _, value in ast.iter_fields(stmt):
                if isinstance(value, list) and value and isinstance(value[0], ast.stmt):
                    yield from walk(value)
                elif isinstance(value, list) and value and isinstance(value[0], (ast.ExceptHandler, ast.match_case)):
                    for sub in value:
                        yield from walk(sub.body)

    yield from walk(fn.body)


def _is_thread_ctor(call: ast.Call) -> bool:
    name = dotted(call.func)
    return name is not None and (name == "Thread" or name.endswith(".Thread"))


def _assigned_names(fn) -> set[str]:
    names = {a.arg for a in fn.args.args + fn.args.kwonlyargs + fn.args.posonlyargs}
    for n in _walk_body(fn):
        if isinstance(n, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            targets = n.targets if isinstance(n, ast.Assign) else [n.target]
            for t in targets:
                for leaf in t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]:
                    if isinstance(leaf, ast.Name):
                        names.add(leaf.id)
        elif isinstance(n, ast.NamedExpr) and isinstance(n.target, ast.Name):
            names.add(n.target.id)
        elif isinstance(n, (ast.For, ast.AsyncFor)) and isinstance(n.target, ast.Name):
            names.add(n.target.id)
    return names


def _has_lock_guard(tfn, cls) -> bool:
    for n in _walk_body(tfn):
        if isinstance(n, (ast.With, ast.AsyncWith)):
            for item in n.items:
                if lock_key(item.context_expr, cls) is not None:
                    return True
    return False


def _mutated_captures(tfn, outer_names: set[str]) -> set[str]:
    local = _assigned_names(tfn)
    nonlocals: set[str] = set()
    for n in _walk_body(tfn):
        if isinstance(n, ast.Nonlocal):
            nonlocals.update(n.names)
    out: set[str] = set()
    for n in _walk_body(tfn):
        if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = n.targets if isinstance(n, ast.Assign) else [n.target]
            for t in targets:
                for leaf in t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]:
                    if isinstance(leaf, ast.Name) and leaf.id in nonlocals:
                        out.add(leaf.id)
                    elif isinstance(leaf, (ast.Attribute, ast.Subscript)):
                        root = _name_root(leaf)
                        if root in outer_names and root not in local:
                            out.add(root)
        elif isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute):
            if n.func.attr in MUTATOR_ATTRS:
                root = _name_root(n.func.value)
                if root in outer_names and root not in local:
                    out.add(root)
    return out


def _lambda_mutations(lam: ast.Lambda, outer_names: set[str]) -> set[str]:
    defaults = {a.arg for a in lam.args.args + lam.args.kwonlyargs}
    out: set[str] = set()
    for n in ast.walk(lam.body):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute):
            if n.func.attr in MUTATOR_ATTRS:
                root = _name_root(n.func.value)
                if root is not None and root in outer_names and root not in defaults:
                    out.add(root)
    return out


# ---------------------------------------------------------------------------
# CCR006: lexical lock-ordering cycles (absorbed TPL004)
# ---------------------------------------------------------------------------
class _OrderVisitor(ast.NodeVisitor):
    """Collect outer->inner edges with the location of the inner acquire."""

    def __init__(self):
        self.edges: dict[tuple[str, str], ast.AST] = {}
        self._held: list[str] = []
        self._cls: list[str] = []

    def visit_ClassDef(self, node):
        self._cls.append(node.name)
        self.generic_visit(node)
        self._cls.pop()

    def _visit_fn(self, node):
        # a new function body starts with nothing lexically held: `with`
        # nesting does not cross call boundaries (that's the dynamic
        # sanitizer's job)
        held, self._held = self._held, []
        self.generic_visit(node)
        self._held = held

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def _visit_with(self, node):
        cls = self._cls[-1] if self._cls else None
        keys = []
        for item in node.items:
            k = lock_key(item.context_expr, cls)
            if k is not None:
                keys.append(k)
                for outer in self._held + keys[:-1]:
                    if outer != k:
                        self.edges.setdefault((outer, k), item.context_expr)
        self._held.extend(keys)
        for stmt in node.body:
            self.visit(stmt)
        if keys:
            del self._held[-len(keys):]

    visit_With = _visit_with
    visit_AsyncWith = _visit_with


def _cycles(edges: dict[tuple[str, str], ast.AST]) -> list[list[str]]:
    graph: dict[str, set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
    out: list[list[str]] = []
    seen_cycles: set[tuple[str, ...]] = set()

    def dfs(start: str, node: str, path: list[str], visited: set[str]):
        for nxt in sorted(graph.get(node, ())):
            if nxt == start:
                cyc = path[:]
                # canonicalize rotation so each cycle reports once
                i = cyc.index(min(cyc))
                canon = tuple(cyc[i:] + cyc[:i])
                if canon not in seen_cycles:
                    seen_cycles.add(canon)
                    out.append(list(canon))
            elif nxt not in visited and len(path) < 8:
                visited.add(nxt)
                dfs(start, nxt, path + [nxt], visited)
                visited.discard(nxt)

    for start in sorted(graph):
        dfs(start, start, [start], {start})
    return out


class LockOrderCycle(Rule):
    id = "CCR006"
    name = "lock-order-cycle"
    summary = "lexical `with` nesting acquires module locks in inconsistent order (potential ABBA deadlock; alias: TPL004)"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        v = _OrderVisitor()
        v.visit(ctx.tree)
        for cyc in _cycles(v.edges):
            # anchor the report at the acquire site of the first inverted
            # edge; every consecutive cycle pair is an edge key by
            # construction, so index directly — drift should fail loudly,
            # not anchor the finding (and its suppression point) elsewhere
            a, b = cyc[0], cyc[1 % len(cyc)]
            node = v.edges[(a, b)]
            order = " -> ".join(cyc + [cyc[0]])
            yield self.finding(
                ctx, node,
                f"lock ordering cycle {order}: two paths acquire these locks in "
                "opposite order; pick one global order (see core/lock_sanitizer.py)",
                context="",
            )


CONCUR_RULES = (
    BlockingUnderLock,
    HotPathDeviceSync,
    GuardedByViolation,
    AcquireWithoutRelease,
    ThreadUnguardedCapture,
    LockOrderCycle,
)


def all_concur_rules(select: set[str] | None = None) -> list[Rule]:
    from ray_tpu.lint.engine import canonical_rule

    rules = [cls() for cls in CONCUR_RULES]
    if select:
        canon = {canonical_rule(s) for s in select}
        rules = [r for r in rules if r.id in canon or r.name in select]
    return rules


def concur_rule_catalog() -> list[tuple[str, str, str]]:
    return [(cls.id, cls.name, cls.summary) for cls in CONCUR_RULES]


def concur_rule_ids() -> set[str]:
    return {cls.id for cls in CONCUR_RULES}
