"""Lock-set dataflow over one function body, plus the two annotation
syntaxes the CCR rules consume.

The lock-set is LEXICAL (RacerD-style "syntactic locks"): a lock key is
a Name/Attribute chain whose final segment looks lock-ish, normalized so
``self.X`` inside class C keys as ``C.X`` (methods of one class share
keys, distinct classes don't alias). Held-ness flows through:

- ``with <lock>:`` items (including multi-item ``with a, b:``);
- standalone ``<lock>.acquire()`` statements, held for the remainder of
  the enclosing block (released early by a matching ``.release()``) —
  deliberately block-scoped, not function-scoped, so hand-over-hand
  chained locking (gcs.py) contributes exactly the region it covers;
- ``# holds-lock: <lock>`` on a ``def`` line, which seeds the entry
  lock-set: the documented caller-holds-lock contract for ``_locked``
  helpers, made machine-readable.

Field annotations: ``# guarded-by: <lock>`` on a ``self.X = ...`` line
in a class body declares that writes to ``self.X`` (and mutator calls on
it) require ``<lock>`` in the lock-set — enforced by CCR003. A bare lock
name is self-relative (``_lock`` in class C means ``C._lock``).
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from ray_tpu.lint.engine import dotted

_LOCKISH = re.compile(r"(?:^|_)(lock|mutex|mu|cond|cv|sem)$", re.IGNORECASE)
GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_.]*)")
HOLDS_LOCK_RE = re.compile(r"#\s*holds-lock:\s*([A-Za-z_][A-Za-z0-9_.]*)")

# container/dict/set/deque/queue methods that mutate their receiver —
# the write shapes CCR003 checks beyond plain assignment
MUTATOR_ATTRS = {
    "append", "appendleft", "extend", "add", "update", "setdefault",
    "pop", "popleft", "popitem", "remove", "discard", "clear", "insert", "put",
}


def lockish(name: str) -> bool:
    return bool(_LOCKISH.search(name.split(".")[-1]))


def lock_key(expr: ast.AST, cls: str | None) -> str | None:
    """Normalized lock key for a lock-ish Name/Attribute chain, or None."""
    name = dotted(expr)
    if name is None or not lockish(name):
        return None
    return normalize_lock_name(name, cls)


def normalize_lock_name(name: str, cls: str | None) -> str:
    """``self.X`` (or bare ``X``, as written in annotations) inside class
    ``cls`` -> ``cls.X``; anything else keeps its dotted spelling."""
    if cls:
        if name.startswith("self."):
            return f"{cls}.{name[len('self.'):]}"
        if "." not in name:
            return f"{cls}.{name}"
    return name


def holds_locks(
    lines: list[str], fn: ast.FunctionDef | ast.AsyncFunctionDef, cls: str | None
) -> frozenset[str]:
    """Lock keys from ``# holds-lock:`` comments on the def line."""
    if not (1 <= fn.lineno <= len(lines)):
        return frozenset()
    return frozenset(
        normalize_lock_name(m, cls) for m in HOLDS_LOCK_RE.findall(lines[fn.lineno - 1])
    )


def guarded_fields(lines: list[str], tree: ast.Module) -> dict[str, dict[str, str]]:
    """{class name: {attr: lock key}} from ``# guarded-by:`` comments on
    ``self.X = ...`` / class-level ``X = ...`` / AnnAssign lines anywhere
    in the class (conventionally ``__init__``)."""
    out: dict[str, dict[str, str]] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        fields: dict[str, str] = {}
        for sub in ast.walk(node):
            if not isinstance(sub, (ast.Assign, ast.AnnAssign)):
                continue
            if not (1 <= sub.lineno <= len(lines)):
                continue
            m = GUARDED_BY_RE.search(lines[sub.lineno - 1])
            if m is None:
                continue
            targets = sub.targets if isinstance(sub, ast.Assign) else [sub.target]
            for t in targets:
                attr = self_attr_root(t)
                if attr is None and isinstance(t, ast.Name):
                    attr = t.id
                if attr is not None:
                    fields[attr] = normalize_lock_name(m.group(1), node.name)
        if fields:
            out[node.name] = fields
    return out


def self_attr_root(expr: ast.AST) -> str | None:
    """The attribute X when ``expr`` is a chain rooted at ``self.X``
    (``self.X``, ``self.X[k]``, ``self.X.y[k]``, ...); None otherwise."""
    while isinstance(expr, (ast.Subscript, ast.Attribute)):
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
        ):
            return expr.attr
        expr = expr.value
    return None


def _stmt_lock_call(stmt: ast.stmt, which: str, cls: str | None) -> str | None:
    """Lock key when ``stmt`` is a standalone ``<lock>.acquire()`` /
    ``<lock>.release()`` expression statement (``which`` picks the
    method). Conditional acquires (``if lock.acquire(False):``) are not
    Expr statements and don't match."""
    if not (isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call)):
        return None
    call = stmt.value
    if not (isinstance(call.func, ast.Attribute) and call.func.attr == which):
        return None
    return lock_key(call.func.value, cls)


def acquire_key(stmt: ast.stmt, cls: str | None) -> str | None:
    return _stmt_lock_call(stmt, "acquire", cls)


def release_key(stmt: ast.stmt, cls: str | None) -> str | None:
    return _stmt_lock_call(stmt, "release", cls)


_SCOPE_BARRIERS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef, ast.Lambda)


def _expr_nodes(node: ast.AST) -> Iterator[ast.AST]:
    """Pre-order walk (parents before children) skipping nested defs and
    lambdas — their bodies run on a different activation, under whatever
    locks THAT caller holds."""
    stack: list[ast.AST] = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, _SCOPE_BARRIERS):
            continue
        yield n
        stack.extend(reversed(list(ast.iter_child_nodes(n))))


def iter_held(
    fn: ast.FunctionDef | ast.AsyncFunctionDef,
    cls: str | None,
    seed: frozenset[str] = frozenset(),
) -> Iterator[tuple[ast.AST, frozenset[str]]]:
    """Yield ``(node, held lock keys)`` for every AST node in ``fn``'s
    lexical body, pre-order (a call is yielded before its argument
    sub-calls, so rules can anchor at the outermost call of a lock
    scope). ``seed`` is the entry lock-set (``# holds-lock:``)."""

    def walk_block(stmts: list[ast.stmt], held: frozenset[str]) -> Iterator[tuple[ast.AST, frozenset[str]]]:
        for stmt in stmts:
            if isinstance(stmt, _SCOPE_BARRIERS):
                continue  # analyzed as its own function
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                keys: set[str] = set()
                for item in stmt.items:
                    yield from ((n, held) for n in _expr_nodes(item.context_expr))
                    if item.optional_vars is not None:
                        yield from ((n, held) for n in _expr_nodes(item.optional_vars))
                    k = lock_key(item.context_expr, cls)
                    if k is not None:
                        keys.add(k)
                yield from walk_block(stmt.body, held | frozenset(keys))
                continue
            ak = acquire_key(stmt, cls)
            if ak is not None:
                yield from ((n, held) for n in _expr_nodes(stmt))
                held = held | {ak}
                continue
            rk = release_key(stmt, cls)
            if rk is not None:
                yield from ((n, held) for n in _expr_nodes(stmt))
                held = held - {rk}
                continue
            # compound statements: expression parts under the current
            # lock-set, statement-list fields recursed (each child block
            # starts from this statement's held set)
            blocks: list[list[ast.stmt]] = []
            exprs: list[ast.AST] = [stmt]
            simple = True
            for name, value in ast.iter_fields(stmt):
                if isinstance(value, list) and value and isinstance(value[0], ast.stmt):
                    blocks.append(value)
                    simple = False
                elif isinstance(value, list) and value and isinstance(value[0], (ast.ExceptHandler, ast.match_case)):
                    simple = False
                    for sub in value:
                        blocks.append(sub.body)
                        for sn, sv in ast.iter_fields(sub):
                            if isinstance(sv, ast.AST):
                                exprs.append(sv)
            if simple:
                yield from ((n, held) for n in _expr_nodes(stmt))
                continue
            yield (stmt, held)
            for name, value in ast.iter_fields(stmt):
                if isinstance(value, ast.AST) and not isinstance(value, ast.stmt):
                    exprs.append(value)
                elif isinstance(value, list) and value and isinstance(value[0], ast.expr):
                    exprs.extend(value)
            for e in exprs[1:]:
                yield from ((n, held) for n in _expr_nodes(e))
            for b in blocks:
                yield from walk_block(b, held)

    yield from walk_block(fn.body, frozenset(seed))


def iter_functions(tree: ast.Module) -> Iterator[tuple[ast.FunctionDef | ast.AsyncFunctionDef, str | None, str]]:
    """Every function def in the module (including nested ones), with its
    enclosing class name (None outside a class — nested defs inside a
    method report the method's class, since ``self`` still binds to it)
    and dotted qualname."""

    def walk(node: ast.AST, cls: str | None, scope: list[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                yield from walk(child, child.name, scope + [child.name])
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = ".".join(scope + [child.name])
                yield child, cls, qual
                yield from walk(child, cls, scope + [child.name])
            elif isinstance(child, (ast.If, ast.Try, ast.With, ast.AsyncWith, ast.For, ast.While)):
                yield from walk(child, cls, scope)

    yield from walk(tree, None, [])
