"""Concurrency-discipline analyzer (CCR catalog): lock-set dataflow,
blocking-under-lock, guarded-by field annotations, and hot-path
device-sync reachability. See ``rules`` for the catalog and ``lockset``
for the dataflow core and annotation syntaxes."""

from ray_tpu.lint.concur.rules import (  # noqa: F401
    CONCUR_RULES,
    all_concur_rules,
    concur_rule_catalog,
    concur_rule_ids,
)
