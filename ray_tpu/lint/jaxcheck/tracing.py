"""Bucket -> jaxpr: abstract tracing and jaxpr-walking helpers.

Everything here is shape arithmetic — ``jax.make_jaxpr`` over
``ShapeDtypeStruct`` leaves compiles nothing and allocates nothing, so
buckets use production-realistic dimensions (the (8,128) tile math in
JXC006 is meaningless on toy shapes).
"""

from __future__ import annotations

import inspect
import math
import os
import sys
from dataclasses import dataclass
from typing import Any, Iterator


def ensure_trace_env(min_devices: int = 8) -> None:
    """Tracing wants CPU and (for shard_map entries) a multi-device mesh.
    Effective only if jax has not been imported yet — under pytest the
    conftest has already configured an 8-device CPU backend, and a live
    TPU backend is equally fine."""
    if "jax" in sys.modules:
        return
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = f"{flags} --xla_force_host_platform_device_count={min_devices}"


def _is_array_leaf(x: Any) -> bool:
    import jax

    return isinstance(x, jax.ShapeDtypeStruct) or (
        hasattr(x, "shape") and hasattr(x, "dtype") and not inspect.isclass(x)
    )


@dataclass
class InLeaf:
    arg: str  # parameter name the leaf belongs to
    path: str  # pretty pytree path, e.g. "cache['k']"
    aval: Any  # ShapedArray
    donated: bool


@dataclass
class TracedBucket:
    bucket: str
    jaxpr: Any  # ClosedJaxpr
    in_leaves: list[InLeaf]
    out_avals: list[Any]
    statics: dict[str, Any]  # python-valued params, by name (JXC004 probes these)


def _key_str(k) -> str:
    name = getattr(k, "name", None)
    if name is not None:
        return f".{name}"
    key = getattr(k, "key", None)
    if key is not None:
        return f"[{key!r}]"
    idx = getattr(k, "idx", None)
    if idx is not None:
        return f"[{idx}]"
    return f"[{k}]"


def trace_bucket(spec, bucket: str, overrides: dict[str, Any] | None = None) -> TracedBucket:
    """Trace one registered bucket to a ClosedJaxpr.

    Array leaves (ShapeDtypeStructs / arrays) become traced arguments;
    every other leaf is static, bound by closure — the same split the
    production ``jax.jit(partial(fn, cfg=cfg))`` makes. ``overrides``
    replaces named static parameters (the JXC004 probe path).
    """
    import jax

    args, kwargs = _build(spec, bucket)
    sig = inspect.signature(spec.fn)
    bound = sig.bind(*args, **kwargs)
    bound.apply_defaults()
    if overrides:
        for k, v in overrides.items():
            if k not in bound.arguments:
                raise KeyError(f"{spec.name}: varying param {k!r} not in bucket {bucket!r} args")
            bound.arguments[k] = v

    dyn_leaves: list[Any] = []
    in_leaves: list[InLeaf] = []
    statics: dict[str, Any] = {}
    # per-parameter: flatten, partition into traced leaves and statics
    placements: list[tuple[str, Any, list[tuple[int, Any]]]] = []  # (param, treedef, [(slot, static)])
    for pname, pval in bound.arguments.items():
        leaves_kp, treedef = jax.tree_util.tree_flatten_with_path(pval)
        slots: list[tuple[int, Any]] = []
        for kp, leaf in leaves_kp:
            if _is_array_leaf(leaf):
                slots.append((len(dyn_leaves), None))
                dyn_leaves.append(jax.ShapeDtypeStruct(leaf.shape, leaf.dtype))
                in_leaves.append(InLeaf(
                    arg=pname,
                    path=pname + "".join(_key_str(k) for k in kp),
                    aval=None,  # filled below from the jaxpr invars
                    donated=pname in spec.donate,
                ))
            else:
                slots.append((-1, leaf))
                if not kp:  # whole param is one static leaf
                    statics[pname] = leaf
        placements.append((pname, treedef, slots))

    def rebuilt(flat):
        import jax as _jax

        rebuilt_args = {}
        for pname, treedef, slots in placements:
            leaves = [flat[i] if i >= 0 else s for i, s in slots]
            rebuilt_args[pname] = _jax.tree_util.tree_unflatten(treedef, leaves)
        return rebuilt_args

    def wrapper(*flat):
        ba = rebuilt(list(flat))
        return spec.fn(**ba)

    closed = jax.make_jaxpr(wrapper)(*dyn_leaves)
    for leaf, var in zip(in_leaves, closed.jaxpr.invars):
        leaf.aval = var.aval
    out_avals = [v.aval for v in closed.jaxpr.outvars]
    return TracedBucket(bucket=bucket, jaxpr=closed, in_leaves=in_leaves, out_avals=out_avals, statics=statics)


def _build(spec, bucket: str) -> tuple[tuple, dict]:
    built = spec.shapes[bucket]()
    if isinstance(built, tuple) and len(built) == 2 and isinstance(built[1], dict) and isinstance(built[0], tuple):
        return built
    if isinstance(built, tuple):
        return built, {}
    raise TypeError(f"{spec.name}[{bucket}]: builder must return (args, kwargs) or an args tuple")


# ---------------------------------------------------------------------------
# jaxpr walking
# ---------------------------------------------------------------------------
def _sub_jaxprs(params: dict) -> Iterator[Any]:
    from jax import core

    for v in params.values():
        vals = v if isinstance(v, (tuple, list)) else (v,)
        for item in vals:
            if isinstance(item, core.ClosedJaxpr):
                yield item.jaxpr
            elif isinstance(item, core.Jaxpr):
                yield item


def iter_jaxprs(closed) -> Iterator[Any]:
    """Every (sub-)Jaxpr reachable from a ClosedJaxpr: the top level plus
    scan/while/cond/pjit/shard_map/custom_* bodies, recursively. Yields
    raw ``core.Jaxpr`` objects (each its own variable scope)."""
    stack = [closed.jaxpr]
    seen: set[int] = set()
    while stack:
        jx = stack.pop()
        if id(jx) in seen:
            continue
        seen.add(id(jx))
        yield jx
        for eqn in jx.eqns:
            stack.extend(_sub_jaxprs(eqn.params))


def iter_eqns(closed) -> Iterator[Any]:
    for jx in iter_jaxprs(closed):
        yield from jx.eqns


def aval_bytes(aval) -> int:
    try:
        return int(math.prod(aval.shape)) * aval.dtype.itemsize
    except (AttributeError, TypeError):
        return 0


def fmt_aval(aval) -> str:
    try:
        return f"{aval.dtype.name}[{','.join(str(d) for d in aval.shape)}]"
    except AttributeError:
        return str(aval)


def canonical(closed) -> str:
    """Stable text form of a jaxpr for equality comparison (JXC004):
    pretty-printing assigns variable names deterministically per trace,
    so two traces of the same program produce identical strings."""
    return str(closed)
