"""jaxcheck driver: import entry modules, trace every registered entry,
run the JXC rules, and hand back engine ``Finding``s.

Failure posture: a registered entry that cannot trace is itself a
finding (``JXCERR``), never a crash and never a silent skip — an entry
that stops tracing is an invariant check that stopped running. The
usual cause is a genuine hazard anyway (a ``jax.device_get``/``np.``
coercion inside the step concretizes a tracer and raises here).
"""

from __future__ import annotations

import ast
import importlib
import os
from dataclasses import replace

from ray_tpu.lint.engine import Finding, finding_suppressed
from ray_tpu.lint.jaxcheck import registry
from ray_tpu.lint.jaxcheck.rules import all_jax_rules
from ray_tpu.lint.jaxcheck.tracing import ensure_trace_env, trace_bucket


def import_entry_modules(modules: tuple[str, ...] = registry.ENTRY_MODULES) -> None:
    """Importing the host modules runs their ``@jaxcheck.entry``
    decorators. Sets up the CPU trace backend first if jax is not yet in."""
    ensure_trace_env()
    for mod in modules:
        importlib.import_module(mod)


def run_jaxcheck(
    root: str | None = None,
    select: set[str] | None = None,
    modules: tuple[str, ...] | None = None,
    entries=None,
) -> list[Finding]:
    """Trace all registered entries (importing ``modules`` first unless an
    explicit ``entries`` list is given) and return rule findings with
    paths relative to ``root``, inline suppressions already applied."""
    root = os.path.abspath(root or os.getcwd())
    if entries is None:
        import_entry_modules(modules if modules is not None else registry.ENTRY_MODULES)
        entries = registry.all_entries()
    rules = all_jax_rules(select)
    out: list[Finding] = []
    lines_cache: dict[str, list[str]] = {}
    for spec in entries:
        rel = os.path.relpath(os.path.abspath(spec.path), root).replace(os.sep, "/") if spec.path else "<entry>"
        if rel not in lines_cache:
            try:
                with open(os.path.abspath(spec.path), encoding="utf-8", errors="replace") as fh:
                    lines_cache[rel] = fh.read().splitlines()
            except OSError:
                lines_cache[rel] = []
        src_lines = lines_cache[rel]
        def_line, arg_lines = _def_location(src_lines, spec)
        spec = replace(spec, path=rel, line=def_line, arg_lines=arg_lines)
        findings: list[Finding] = []
        traced = []
        for bucket in sorted(spec.shapes):
            try:
                traced.append(trace_bucket(spec, bucket))
            except Exception as e:  # noqa: BLE001 — any trace failure is the finding
                findings.append(Finding(
                    rule="JXCERR", path=rel, line=spec.line, col=0,
                    message=(
                        f"entry failed to trace bucket '{bucket}': {type(e).__name__}: "
                        f"{str(e).splitlines()[0] if str(e) else ''} (a concretization error "
                        "here usually means a host sync inside the step)"
                    ),
                    context=f"jaxcheck:{spec.name}",
                ))
        for rule in rules:
            # same posture as bucket tracing: a rule that blows up (e.g. a
            # JXC004 probe value whose re-trace raises) degrades to a
            # finding, never a crashed lint run
            try:
                findings.extend(rule.check(spec, traced))
            except Exception as e:  # noqa: BLE001
                findings.append(Finding(
                    rule="JXCERR", path=rel, line=spec.line, col=0,
                    message=(
                        f"rule {rule.id} failed on this entry: {type(e).__name__}: "
                        f"{str(e).splitlines()[0] if str(e) else ''}"
                    ),
                    context=f"jaxcheck:{spec.name}",
                ))
        out.extend(f for f in findings if not finding_suppressed(src_lines, f))
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def _def_location(src_lines: list[str], spec) -> tuple[int, dict[str, int]]:
    """(def line, {param -> signature line}) for the registered function.
    ``co_firstlineno`` points at the first decorator; findings anchor at
    the ``def`` (entry-wide rules) or the parameter's own signature line
    (per-argument rules), which is where inline disables + rationale
    comments live — a multi-line signature scopes a disable to one arg."""
    name = getattr(spec.fn, "__name__", "")
    try:
        tree = ast.parse("\n".join(src_lines))
    except SyntaxError:
        return spec.line, {}
    best = None
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and node.name == name:
            # pick the def nearest (at or after) the registration line
            if best is None or abs(node.lineno - spec.line) < abs(best.lineno - spec.line):
                best = node
    if best is None:
        return spec.line, {}
    a = best.args
    params = [*a.posonlyargs, *a.args, *(p for p in [a.vararg] if p), *a.kwonlyargs, *(p for p in [a.kwarg] if p)]
    return best.lineno, {p.arg: p.lineno for p in params}
