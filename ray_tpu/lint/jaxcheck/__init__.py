"""jaxcheck: trace-based jaxpr/SPMD hazard analysis.

tpulint's AST rules see source text; jaxcheck sees the *program*. Entry
points register themselves with ``@jaxcheck.entry(shapes=...)`` (a
decorator on the module-level fns the production ``jax.jit`` calls
wrap), and the checker traces each one to a jaxpr with abstract inputs —
no FLOPs, no devices touched — then checks TPU invariants no AST rule
can express: donation coverage of the hot-loop buffers (JXC001), host
round trips inside a step (JXC002), silent bf16→f32 upcasts on
flops-dominant ops (JXC003), Python scalars that drive per-value
recompilation (JXC004), collective axis names that escape the declared
mesh or diverge across cond branches (JXC005), and (8,128) tile padding
waste (JXC006).

Findings flow through the same engine as the AST rules: identical
``Finding`` objects, fingerprints, baseline budgets, ``--select``, and
inline ``# tpulint: disable=JXC00x`` suppression on the registered
def's line.
"""

from ray_tpu.lint.jaxcheck.registry import (  # noqa: F401
    ENTRY_MODULES,
    EntrySpec,
    all_entries,
    clear_registry,
    entry,
    get_entry,
)
from ray_tpu.lint.jaxcheck.driver import import_entry_modules, run_jaxcheck  # noqa: F401
from ray_tpu.lint.jaxcheck.rules import jax_rule_catalog, jax_rule_ids  # noqa: F401
