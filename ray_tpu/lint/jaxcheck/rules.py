"""The six jaxpr-level rules. Each checks one TPU invariant the AST pass
cannot see, over the traced buckets of one entry.

Findings reuse the engine's ``Finding`` dataclass and anchor at the
registered def's line — that line is where an inline
``# tpulint: disable=JXC00x`` plus rationale comment lives, and the
fingerprint context is ``jaxcheck:<entry name>`` so baselines survive
any edit that doesn't change the traced program's verdict.
"""

from __future__ import annotations

import math
from collections import Counter
from typing import Iterator

from ray_tpu.lint.engine import Finding
from ray_tpu.lint.jaxcheck.tracing import (
    TracedBucket,
    _sub_jaxprs,
    aval_bytes,
    canonical,
    fmt_aval,
    iter_eqns,
    iter_jaxprs,
    trace_bucket,
)

# TPU vector tiling: the last two dims of an operand land in (sublane,
# lane) = (8, 128) tiles (f32 granularity; narrower dtypes pack more
# sublanes but never fewer — (8, 128) is the conservative floor the
# ISSUE's budget is defined against).
_TILE = (8, 128)

_CALLBACK_PRIMS = {"pure_callback", "io_callback", "debug_callback", "callback"}
_COLLECTIVE_PRIMS = {
    "psum", "pmax", "pmin", "ppermute", "pbroadcast", "all_gather",
    "all_to_all", "reduce_scatter", "psum_scatter", "axis_index",
}


class JaxRule:
    id = "JXC000"
    name = "abstract"
    summary = ""

    def check(self, spec, traced: list[TracedBucket]) -> Iterator[Finding]:  # pragma: no cover
        raise NotImplementedError

    def finding(self, spec, message: str, arg: str | None = None) -> Finding:
        # path is rewritten root-relative by the driver; per-argument
        # findings anchor at the argument's signature line when known
        return Finding(
            rule=self.id, path=spec.path,
            line=spec.arg_lines.get(arg, spec.line) if arg else spec.line, col=0,
            message=message, context=f"jaxcheck:{spec.name}",
        )


# ------------------------------------------------------------------ JXC001
class UndonatedMutatedInput(JaxRule):
    id = "JXC001"
    name = "undonated-mutated-input"
    summary = "large input whose shape reappears in the output is not donated (a fresh copy every step)"

    def check(self, spec, traced):
        flagged: set[str] = set()
        for tb in traced:
            out_pool: Counter = Counter((tuple(a.shape), str(a.dtype)) for a in tb.out_avals)
            # donated inputs claim their output buffers first: a donated
            # cache consumes the new cache, leaving only genuinely
            # unclaimed outputs to implicate undonated inputs
            for leaf in tb.in_leaves:
                if leaf.donated:
                    key = (tuple(leaf.aval.shape), str(leaf.aval.dtype))
                    if out_pool[key] > 0:
                        out_pool[key] -= 1
            for leaf in tb.in_leaves:
                if leaf.donated or leaf.path in flagged:
                    continue
                if aval_bytes(leaf.aval) < spec.donate_bytes:
                    continue
                key = (tuple(leaf.aval.shape), str(leaf.aval.dtype))
                if out_pool[key] > 0:
                    out_pool[key] -= 1
                    flagged.add(leaf.path)
                    yield self.finding(spec, (
                        f"input '{leaf.path}' matches an output buffer's shape/dtype but is "
                        "not donated — the step allocates a second copy every call; add it to "
                        "donate_argnums (or disable with a rationale if the host still reads it)"
                    ), arg=leaf.arg)


# ------------------------------------------------------------------ JXC002
class HostRoundTrip(JaxRule):
    id = "JXC002"
    name = "host-round-trip"
    summary = "host callback primitive inside a traced step (device pipeline stalls every call)"

    def check(self, spec, traced):
        seen: set[str] = set()
        for tb in traced:
            for eqn in iter_eqns(tb.jaxpr):
                pname = eqn.primitive.name
                if pname in _CALLBACK_PRIMS and pname not in seen:
                    seen.add(pname)
                    cb = eqn.params.get("callback", None)
                    what = getattr(cb, "__name__", None) or str(cb or pname)
                    yield self.finding(spec, (
                        f"traced program contains host callback primitive '{pname}' ({what}) — "
                        "every step round-trips to the host and stalls the device pipeline; "
                        "move it out of the hot path or batch it behind the step"
                    ))


# ------------------------------------------------------------------ JXC003
def _dot_flops(eqn) -> float:
    out = eqn.outvars[0].aval
    (lhs_c, _), _ = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval
    k = math.prod(lhs.shape[d] for d in lhs_c) or 1
    return 2.0 * math.prod(out.shape) * k


class SilentUpcastDominantOp(JaxRule):
    id = "JXC003"
    name = "silent-upcast-dominant-op"
    summary = "flops-dominant matmul computes in f32 on operands upcast from bf16 (2x bandwidth, slower MXU path)"

    def check(self, spec, traced):
        seen: set[str] = set()
        for tb in traced:
            dots: list[tuple] = []  # (eqn, producers) per sub-jaxpr scope
            total = 0.0
            for jx in iter_jaxprs(tb.jaxpr):
                producers = {}
                for eqn in jx.eqns:
                    for ov in eqn.outvars:
                        producers[ov] = eqn
                for eqn in jx.eqns:
                    if eqn.primitive.name == "dot_general":
                        fl = _dot_flops(eqn)
                        total += fl
                        dots.append((eqn, producers, fl))
            for eqn, producers, fl in dots:
                if total <= 0 or fl < spec.flops_frac * total:
                    continue
                for iv in eqn.invars:
                    aval = getattr(iv, "aval", None)
                    if aval is None or str(aval.dtype) != "float32":
                        continue
                    prod_eqn = producers.get(iv)
                    if (
                        prod_eqn is not None
                        and prod_eqn.primitive.name == "convert_element_type"
                        and str(prod_eqn.invars[0].aval.dtype) == "bfloat16"
                    ):
                        key = fmt_aval(aval)
                        if key in seen:
                            continue
                        seen.add(key)
                        yield self.finding(spec, (
                            f"flops-dominant dot_general consumes {key} upcast from bf16 — "
                            "the matmul runs off-MXU-fast-path at double the HBM traffic; keep "
                            "operands bf16 and set preferred_element_type=float32 for the accumulate"
                        ))


# ------------------------------------------------------------------ JXC004
class RecompilationDriver(JaxRule):
    id = "JXC004"
    name = "recompilation-driver"
    summary = "per-request Python scalar is baked into the traced program (a recompile for every distinct value)"

    def check(self, spec, traced):
        if not spec.varying:
            return
        for pname, probe in spec.varying.items():
            v1, v2 = probe
            bucket = next((tb.bucket for tb in traced if pname in tb.statics), None)
            if bucket is None:
                continue
            j1 = canonical(trace_bucket(spec, bucket, overrides={pname: v1}).jaxpr)
            j2 = canonical(trace_bucket(spec, bucket, overrides={pname: v2}).jaxpr)
            if j1 != j2:
                yield self.finding(spec, (
                    f"Python scalar '{pname}' is baked into the traced program (jaxprs differ "
                    f"between probe values {v1!r} and {v2!r}) — every distinct runtime value "
                    "forces a recompile; pass it as a traced 0-d array or quantize it into "
                    "registered shape buckets"
                ))


# ------------------------------------------------------------------ JXC005
def _eqn_axis_names(eqn) -> tuple[str, ...]:
    axes = eqn.params.get("axes", eqn.params.get("axis_name", ()))
    if isinstance(axes, (str, int)):
        axes = (axes,)
    return tuple(a for a in axes if isinstance(a, str))


def _collective_axes(jaxpr_like) -> set[str]:
    out: set[str] = set()
    stack = [jaxpr_like]
    while stack:
        jx = stack.pop()
        for eqn in jx.eqns:
            if eqn.primitive.name in _COLLECTIVE_PRIMS:
                out.update(_eqn_axis_names(eqn))
            stack.extend(_sub_jaxprs(eqn.params))
    return out


class CollectiveAxisMismatch(JaxRule):
    id = "JXC005"
    name = "collective-axis-mismatch"
    summary = "collective over an axis name outside the declared mesh, or differing across cond branches"

    def check(self, spec, traced):
        declared = set(spec.mesh_axes)
        seen: set[str] = set()
        for tb in traced:
            for eqn in iter_eqns(tb.jaxpr):
                pname = eqn.primitive.name
                if pname in _COLLECTIVE_PRIMS:
                    for ax in _eqn_axis_names(eqn):
                        if ax not in declared and ax not in seen:
                            seen.add(ax)
                            yield self.finding(spec, (
                                f"collective '{pname}' runs over axis '{ax}' which is not in the "
                                f"entry's declared mesh axes {tuple(sorted(declared))} — the program "
                                "cannot lower on the production mesh (axis-name drift)"
                            ))
                elif pname == "cond":
                    branches = eqn.params.get("branches", ())
                    axis_sets = [_collective_axes(b.jaxpr if hasattr(b, "jaxpr") else b) for b in branches]
                    if axis_sets and any(s != axis_sets[0] for s in axis_sets[1:]):
                        key = "cond:" + "/".join(sorted(",".join(sorted(s)) for s in axis_sets))
                        if key not in seen:
                            seen.add(key)
                            yield self.finding(spec, (
                                "cond branches perform collectives over differing axis sets "
                                f"({' vs '.join(repr(sorted(s)) for s in axis_sets)}) — under "
                                "shard_map a divergent predicate deadlocks the mesh mid-collective; "
                                "hoist the collective out of the branch"
                            ))


# ------------------------------------------------------------------ JXC006
def _tile_waste(aval) -> float:
    shape = aval.shape
    if len(shape) < 2:
        return 0.0
    sub, lane = _TILE
    d2, d1 = shape[-2], shape[-1]
    if d2 == 0 or d1 == 0:
        return 0.0
    padded = math.ceil(d2 / sub) * sub * math.ceil(d1 / lane) * lane
    return 1.0 - (d2 * d1) / padded


class PaddingWaste(JaxRule):
    id = "JXC006"
    name = "padding-waste"
    summary = "trailing dims far off the (8,128) tile: HBM and MXU cycles spent on padding"

    def check(self, spec, traced):
        flagged: set[str] = set()
        for tb in traced:
            for leaf in tb.in_leaves:
                if leaf.path in flagged or aval_bytes(leaf.aval) < spec.pad_min_bytes:
                    continue
                waste = _tile_waste(leaf.aval)
                if waste > spec.pad_waste:
                    flagged.add(leaf.path)
                    yield self.finding(spec, (
                        f"input '{leaf.path}' trailing dims waste {waste:.0%} of their (8,128) "
                        "tiles — the buffer pads to the tile grid in HBM and the MXU streams the "
                        "padding; fold/reorder dims so the last two approach tile multiples"
                    ))


_JAX_RULES = (
    UndonatedMutatedInput,
    HostRoundTrip,
    SilentUpcastDominantOp,
    RecompilationDriver,
    CollectiveAxisMismatch,
    PaddingWaste,
)


def all_jax_rules(select: set[str] | None = None) -> list[JaxRule]:
    rules = [cls() for cls in _JAX_RULES]
    if select:
        rules = [r for r in rules if r.id in select or r.name in select]
    return rules


def jax_rule_ids() -> set[str]:
    return {cls.id for cls in _JAX_RULES}


def jax_rule_catalog() -> list[tuple[str, str, str]]:
    return [(cls.id, cls.name, cls.summary) for cls in _JAX_RULES]
