"""Entry-point registry: which functions jaxcheck traces, and with what.

An entry is a module-level function plus everything the checker cannot
infer from source: the abstract input shapes production calls it with
(``shapes`` — named buckets, mirroring the engine's pow-2 padding
buckets), which arguments the production ``jax.jit`` donates
(``donate``), which mesh axis names its collectives may use
(``mesh_axes``), and which closure-bound Python scalars vary per request
at runtime (``varying`` — the JXC004 probes).

Bucket builders return ``(args, kwargs)`` exactly as the production
call site passes them, with two conventions:

- array arguments are ``jax.ShapeDtypeStruct`` leaves (build whole
  pytrees with ``jax.eval_shape``) — traced abstractly, never allocated;
- anything else (configs, ints, floats, strings) is STATIC: bound into
  the closure before tracing, mirroring how production binds it via
  ``functools.partial``/default args. A value the production jit traces
  (a per-step scalar) must therefore be given as a 0-d
  ``ShapeDtypeStruct``, not a Python number — that distinction is
  exactly what JXC004 audits.

Registration happens at import of the host module and must stay cheap:
the decorator records the spec and returns the function unchanged;
builders run only when a check runs.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

# Modules whose import registers the production entry points. Kept here —
# not in CLI code — so tests and the CI gate agree on coverage.
ENTRY_MODULES = (
    "ray_tpu.llm.model_runner",
    "ray_tpu.llm.disagg.scatter",
    "ray_tpu.llm.kvplane.quant",
    "ray_tpu.llm.pallas.paged_attn",
    "ray_tpu.llm.spec.drafter",
    "ray_tpu.llm.spec.verify",
    "ray_tpu.parallel.train_step",
    "ray_tpu.parallel.pipeline",
    "ray_tpu.collective.ici",
)


@dataclass
class EntrySpec:
    name: str  # "llm.fused_step" — stable id, used in finding contexts
    fn: Callable
    shapes: dict[str, Callable[[], tuple]]  # bucket name -> () -> (args, kwargs)
    donate: tuple[str, ...] = ()  # parameter names the production jit donates
    mesh_axes: tuple[str, ...] = ()  # axis names collectives may legally use
    varying: dict[str, tuple] = field(default_factory=dict)  # param -> (v1, v2) probe values
    donate_bytes: int = 1 << 20  # JXC001 floor: smaller undonated buffers pass
    pad_min_bytes: int = 1 << 20  # JXC006 floor
    pad_waste: float = 0.25  # JXC006 budget: flag waste beyond this fraction
    flops_frac: float = 0.10  # JXC003: "dominant" = >= this fraction of entry dot flops
    path: str = ""  # abs source file of the registered def
    line: int = 0  # line of the def (where inline disables live)
    # parameter name -> signature line (driver-filled from the source AST);
    # per-argument findings (JXC001) anchor here so a multi-line signature
    # gives per-argument inline-disable granularity
    arg_lines: dict[str, int] = field(default_factory=dict)


_REGISTRY: dict[str, EntrySpec] = {}


def entry(
    name: str,
    shapes: dict[str, Callable[[], tuple]],
    donate: tuple[str, ...] = (),
    mesh_axes: tuple[str, ...] = (),
    varying: dict[str, tuple] | None = None,
    donate_bytes: int = 1 << 20,
    pad_min_bytes: int = 1 << 20,
    pad_waste: float = 0.25,
    flops_frac: float = 0.10,
):
    """Register the decorated function as a jaxcheck entry point."""

    def wrap(fn: Callable) -> Callable:
        code = getattr(fn, "__code__", None)
        _REGISTRY[name] = EntrySpec(
            name=name,
            fn=fn,
            shapes=dict(shapes),
            donate=tuple(donate),
            mesh_axes=tuple(mesh_axes),
            varying=dict(varying or {}),
            donate_bytes=donate_bytes,
            pad_min_bytes=pad_min_bytes,
            pad_waste=pad_waste,
            flops_frac=flops_frac,
            path=code.co_filename if code else "",
            line=code.co_firstlineno if code else 0,
        )
        return fn

    return wrap


def all_entries() -> list[EntrySpec]:
    return [_REGISTRY[k] for k in sorted(_REGISTRY)]


def get_entry(name: str) -> EntrySpec | None:
    return _REGISTRY.get(name)


def clear_registry() -> None:
    """Test hook: forget everything. Note module imports are cached, so
    re-registering after a clear needs ``importlib.reload`` of the entry
    modules, not just ``import_entry_modules``."""
    _REGISTRY.clear()
