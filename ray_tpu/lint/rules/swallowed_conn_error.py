"""TPL007: ``except ConnectionError: pass`` — a dead peer vanishes
silently.

In a distributed runtime a ConnectionError is a STATE TRANSITION (peer
died, failover owed), not noise: a handler whose entire body is ``pass``
drops that transition on the floor. The round-5 ADVICE bug was exactly
this shape — ``send_call`` raising before its ``_CallRec`` registered,
the swallow leaving return oids PENDING forever so ``ray.get()`` hung.
A bare swallow is only safe when some OTHER mechanism provably observes
the death (say so in a comment and suppress, or better: handle it).
Plain ``except OSError`` cleanup swallows (close/unlink paths) are not
flagged — only the ConnectionError family carries failover obligations.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ray_tpu.lint.engine import FileContext, Finding, Rule, ScopedVisitor, dotted

_CONN_ERRORS = {
    "ConnectionError", "ConnectionResetError", "ConnectionAbortedError",
    "ConnectionRefusedError", "BrokenPipeError",
}


def _names(type_expr: ast.AST | None) -> list[str]:
    if type_expr is None:
        return []
    exprs = list(type_expr.elts) if isinstance(type_expr, ast.Tuple) else [type_expr]
    out = []
    for e in exprs:
        name = dotted(e)
        if name is not None:
            out.append(name.split(".")[-1])
    return out


class _Visitor(ScopedVisitor):
    def __init__(self, rule, ctx):
        super().__init__()
        self.rule = rule
        self.ctx = ctx
        self.out: list[Finding] = []

    def visit_Try(self, node: ast.Try):
        for handler in node.handlers:
            caught = set(_names(handler.type))
            conn = sorted(caught & _CONN_ERRORS)
            if conn and len(handler.body) == 1 and isinstance(handler.body[0], ast.Pass):
                self.out.append(self.rule.finding(
                    self.ctx, handler,
                    f"swallowed {'/'.join(conn)} with a bare pass: the peer-death event is "
                    "lost (pending work never fails over); complete/fail the in-flight "
                    "state or record why another path observes it",
                    context=self.qualname,
                ))
        self.generic_visit(node)


class SwallowedConnError(Rule):
    id = "TPL007"
    name = "swallowed-connection-error"
    summary = "except ConnectionError: pass — peer-death transition silently dropped, failover lost"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        v = _Visitor(self, ctx)
        v.visit(ctx.tree)
        yield from v.out
