"""TPL001: blocking ``ray.get()``/``ray.wait()`` inside an actor method or
an async coroutine.

An actor method blocking on ``get`` of another task running on the SAME
actor (or on a cycle of actors) deadlocks with no timeout to save it; in
an ``async def`` the call parks the whole event loop, starving every
other coroutine sharing it (the serve proxy, async actor method queues).
The head path can't see either: the caller looks merely "busy".

Interprocedural, one level: an actor method (or coroutine) that calls a
module-level sync helper whose body does an unbounded get is the same
hazard hoisted behind a function call — the call site is flagged, naming
the helper. Module-level helpers are exactly the defs the lexical rule
is silent on (methods of the actor class are already visited in actor
context), so the two passes never double-report one hazard.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ray_tpu.lint.callgraph import CallGraph, blocking_ray_call
from ray_tpu.lint.engine import FileContext, Finding, Rule, ScopedVisitor, has_decorator


class _Visitor(ScopedVisitor):
    def __init__(self, rule: "BlockingGetInActor", ctx: FileContext):
        super().__init__()
        self.rule = rule
        self.ctx = ctx
        self.graph = CallGraph(ctx.tree)
        self.out: list[Finding] = []
        self._actor_depth = 0  # inside a @remote class body
        self._fn_kind: list[str] = []  # "sync" | "async" per enclosing function

    def enter_scope(self, node):
        if isinstance(node, ast.ClassDef):
            self._actor_depth += has_decorator(node, ("remote",))
        else:
            self._fn_kind.append("async" if isinstance(node, ast.AsyncFunctionDef) else "sync")

    def leave_scope(self, node):
        if isinstance(node, ast.ClassDef):
            self._actor_depth -= has_decorator(node, ("remote",))
        else:
            self._fn_kind.pop()

    def visit_Call(self, node: ast.Call):
        hit = blocking_ray_call(node)
        in_async = bool(self._fn_kind) and self._fn_kind[-1] == "async"
        in_actor_method = self._actor_depth > 0 and bool(self._fn_kind)
        if hit is not None:
            name, bounded = hit
            if bounded and not in_async:
                pass  # a deadlined get inside an actor surfaces instead of deadlocking
            elif in_async:
                self.out.append(self.rule.finding(
                    self.ctx, node,
                    f"blocking {name}() inside an async coroutine parks the event loop; "
                    "await an async variant or hand off to a thread",
                    context=self.qualname,
                ))
            elif in_actor_method:
                self.out.append(self.rule.finding(
                    self.ctx, node,
                    f"blocking {name}() inside an actor method risks actor deadlock "
                    "(self-call or actor-cycle waits forever); restructure or pass a timeout",
                    context=self.qualname,
                ))
        if in_async or in_actor_method:
            self._check_callee(node, in_async)
        self.generic_visit(node)

    def _check_callee(self, node: ast.Call, in_async: bool):
        """One-level interprocedural step: a bare call to a module-level
        SYNC helper whose body blocks. Mirrors the lexical gate exactly:
        a timeout bound clears the actor-deadlock case but NOT the async
        case (a bounded get still parks the event loop for its duration).
        (An async callee is flagged on its own body by the lexical pass.)"""
        callee = self.graph.resolve(node)
        if callee is None or isinstance(callee, ast.AsyncFunctionDef):
            return
        for _, blocking_name, bounded in self.graph.blocking_calls(callee):
            if bounded and not in_async:
                continue  # deadlined get inside an actor-called helper is fine
            where = "parks the event loop" if in_async else "risks actor deadlock"
            self.out.append(self.rule.finding(
                self.ctx, node,
                f"call to local helper {callee.name}() which does a blocking "
                f"{blocking_name}() — {where} one call deeper; "
                "bound the get by the remaining deadline or restructure the helper",
                context=self.qualname,
            ))
            return


class BlockingGetInActor(Rule):
    id = "TPL001"
    name = "blocking-get-in-actor"
    summary = "ray.get()/ray.wait() called from an actor method or async coroutine (deadlock / event-loop stall)"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        v = _Visitor(self, ctx)
        v.visit(ctx.tree)
        yield from v.out
