"""TPL001: blocking ``ray.get()``/``ray.wait()`` inside an actor method or
an async coroutine.

An actor method blocking on ``get`` of another task running on the SAME
actor (or on a cycle of actors) deadlocks with no timeout to save it; in
an ``async def`` the call parks the whole event loop, starving every
other coroutine sharing it (the serve proxy, async actor method queues).
The head path can't see either: the caller looks merely "busy".
"""

from __future__ import annotations

import ast
from typing import Iterator

from ray_tpu.lint.engine import FileContext, Finding, Rule, ScopedVisitor, call_keyword, dotted, has_decorator

_BLOCKING = {"get", "wait"}
_MODULES = {"ray", "ray_tpu", "rt"}


class _Visitor(ScopedVisitor):
    def __init__(self, rule: "BlockingGetInActor", ctx: FileContext):
        super().__init__()
        self.rule = rule
        self.ctx = ctx
        self.out: list[Finding] = []
        self._actor_depth = 0  # inside a @remote class body
        self._fn_kind: list[str] = []  # "sync" | "async" per enclosing function

    def enter_scope(self, node):
        if isinstance(node, ast.ClassDef):
            self._actor_depth += has_decorator(node, ("remote",))
        else:
            self._fn_kind.append("async" if isinstance(node, ast.AsyncFunctionDef) else "sync")

    def leave_scope(self, node):
        if isinstance(node, ast.ClassDef):
            self._actor_depth -= has_decorator(node, ("remote",))
        else:
            self._fn_kind.pop()

    def visit_Call(self, node: ast.Call):
        name = dotted(node.func)
        if name is not None:
            parts = name.split(".")
            if len(parts) == 2 and parts[0] in _MODULES and parts[1] in _BLOCKING:
                in_async = bool(self._fn_kind) and self._fn_kind[-1] == "async"
                in_actor_method = self._actor_depth > 0 and bool(self._fn_kind)
                bounded = call_keyword(node, "timeout") is not None
                if bounded and not in_async:
                    pass  # a deadlined get inside an actor surfaces instead of deadlocking
                elif in_async:
                    self.out.append(self.rule.finding(
                        self.ctx, node,
                        f"blocking {name}() inside an async coroutine parks the event loop; "
                        "await an async variant or hand off to a thread",
                        context=self.qualname,
                    ))
                elif in_actor_method:
                    self.out.append(self.rule.finding(
                        self.ctx, node,
                        f"blocking {name}() inside an actor method risks actor deadlock "
                        "(self-call or actor-cycle waits forever); restructure or pass a timeout",
                        context=self.qualname,
                    ))
        self.generic_visit(node)


class BlockingGetInActor(Rule):
    id = "TPL001"
    name = "blocking-get-in-actor"
    summary = "ray.get()/ray.wait() called from an actor method or async coroutine (deadlock / event-loop stall)"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        v = _Visitor(self, ctx)
        v.visit(ctx.tree)
        yield from v.out
