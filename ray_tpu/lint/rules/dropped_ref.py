"""TPL002: fire-and-forget ``.remote()`` whose ObjectRef is dropped.

A ``f.remote()`` expression statement discards the only handle to the
task's result: if the task raises, the error completes an ObjectRef
nobody will ever ``get``, so the failure is silent (and under
ref-counting the return may be freed before the task even finishes).
Bind the ref — even to ``_last =`` for ordering-only calls — or get it.

Interprocedural, one level: ``kick(x)`` as a bare statement, where
``kick`` is a module-level helper whose ``return`` hands back a
``.remote()`` ref, drops that ref at the CALL site — the helper itself
is clean, so only the caller can be flagged.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ray_tpu.lint.callgraph import CallGraph
from ray_tpu.lint.engine import FileContext, Finding, Rule, ScopedVisitor


def _is_remote_call(expr: ast.AST) -> bool:
    """Matches ``x.remote(...)`` and ``x.options(...).remote(...)``."""
    return (
        isinstance(expr, ast.Call)
        and isinstance(expr.func, ast.Attribute)
        and expr.func.attr == "remote"
    )


class _Visitor(ScopedVisitor):
    def __init__(self, rule: "DroppedObjectRef", ctx: FileContext):
        super().__init__()
        self.rule = rule
        self.ctx = ctx
        self.graph = CallGraph(ctx.tree)
        self.out: list[Finding] = []

    def visit_Expr(self, node: ast.Expr):
        # `await f.remote()` wraps the call in Await: the result was
        # consumed by the coroutine machinery, not dropped — skip.
        if _is_remote_call(node.value):
            self.out.append(self.rule.finding(
                self.ctx, node,
                "ObjectRef from .remote() is dropped; task errors vanish silently "
                "(bind the ref or ray.get it)",
                context=self.qualname,
            ))
        elif isinstance(node.value, ast.Call):
            callee = self.graph.resolve(node.value)
            if callee is not None and self.graph.returns_object_ref(callee):
                self.out.append(self.rule.finding(
                    self.ctx, node,
                    f"result of {callee.name}() is dropped but the helper returns an "
                    "ObjectRef from .remote(); task errors vanish silently "
                    "(bind the ref or ray.get it)",
                    context=self.qualname,
                ))
        self.generic_visit(node)


class DroppedObjectRef(Rule):
    id = "TPL002"
    name = "dropped-object-ref"
    summary = "ObjectRef returned by .remote() is discarded, losing the task's error channel"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        v = _Visitor(self, ctx)
        v.visit(ctx.tree)
        yield from v.out
