"""TPL006: unbounded blocking call inside a loop that owns a caller
timeout.

The wait/get/pull paths all share one shape: the caller hands in a
``timeout``/``deadline``, the function spins until it expires. A
``recv``/``request``/``wait`` inside that loop with NO bound of its own
can sit far past the caller's deadline on a slow peer (the round-5
``wait_mixed`` bug: a 0.1s ``ray.wait`` blocking ~10s per id inside
``owned_ready``). Every blocking call inside a deadline loop must carry
its own timeout — ideally derived from the remaining deadline.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ray_tpu.lint.engine import FileContext, Finding, Rule, call_keyword, dotted

_DEADLINE_PARAMS = {"timeout", "deadline", "timeout_s", "deadline_s", "timeout_ms"}
# attribute calls that block until data/events arrive; `timeout=` (or a
# positional beyond the data args) is their only bound
_BLOCKING_ATTRS = {"recv", "recv_into", "recvfrom", "request", "accept", "join"}
_SLEEP_FLOOR_S = 1.0  # fixed sleeps >= this inside a deadline loop defeat its granularity


def _own_nodes(fn: ast.AST):
    """ast.walk restricted to ``fn``'s own scope: everything inside a
    nested def/class is excluded — a helper's local ``timeout`` must not
    make the OUTER function 'own' a deadline (and a helper's settimeout
    must not vouch for the outer body's socket ops). Single pruned pass
    (an every-function skip-set rebuild made this rule dominate the
    full-tree wall clock)."""
    stack: list[ast.AST] = [fn]
    while stack:
        n = stack.pop()
        if n is not fn and isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            continue
        yield n
        stack.extend(ast.iter_child_nodes(n))


def _owns_deadline(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> bool:
    args = fn.args
    names = {a.arg for a in args.args + args.kwonlyargs + args.posonlyargs}
    if names & _DEADLINE_PARAMS:
        return True
    for n in _own_nodes(fn):
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store) and n.id in _DEADLINE_PARAMS:
            return True
    return False


def _settimeout_present(fn: ast.AST) -> bool:
    for n in _own_nodes(fn):
        if isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) and n.func.attr in ("settimeout", "setblocking"):
            return True
    return False


class _LoopVisitor(ast.NodeVisitor):
    def __init__(self, rule, ctx, fn, qual: str):
        self.rule = rule
        self.ctx = ctx
        self.qual = qual
        self.out: list[Finding] = []
        self._loop_depth = 0
        self._sock_bounded = _settimeout_present(fn)

    def _loop(self, node):
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_For = _loop
    visit_AsyncFor = _loop
    visit_While = _loop

    def _nested_fn(self, node):
        pass  # nested defs own their own deadlines (or lack thereof)

    visit_FunctionDef = _nested_fn
    visit_AsyncFunctionDef = _nested_fn
    visit_ClassDef = _nested_fn

    def visit_Call(self, node: ast.Call):
        if self._loop_depth > 0:
            self._check(node)
        self.generic_visit(node)

    def _check(self, node: ast.Call):
        name = dotted(node.func)
        if isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            if attr in _BLOCKING_ATTRS:
                if call_keyword(node, "timeout", "deadline") is not None:
                    return
                if attr in ("recv", "recv_into", "recvfrom", "accept") and self._sock_bounded:
                    return  # settimeout in this function bounds the socket ops
                if attr == "join" and (node.args or node.keywords):
                    return  # thread.join(t) is bounded
                self.out.append(self.rule.finding(
                    self.ctx, node,
                    f".{attr}() inside this deadline loop has no timeout of its own; "
                    "a slow peer blocks past the caller's deadline — bound it by the "
                    "remaining deadline",
                    context=self.qual,
                ))
                return
            if attr == "get" and not node.args and not node.keywords:
                # queue-style zero-arg .get() blocks forever; dict-style
                # .get(k, d) carries args and is not a blocking call
                self.out.append(self.rule.finding(
                    self.ctx, node,
                    ".get() with no timeout inside this deadline loop blocks until an item "
                    "arrives; use .get(timeout=...) bounded by the remaining deadline",
                    context=self.qual,
                ))
                return
            if attr == "wait" and not node.args and call_keyword(node, "timeout") is None:
                self.out.append(self.rule.finding(
                    self.ctx, node,
                    ".wait() with no timeout inside this deadline loop can block forever "
                    "if the event is never set; pass the remaining deadline",
                    context=self.qual,
                ))
                return
        if name in ("time.sleep", "sleep") and node.args:
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, (int, float)) and arg.value >= _SLEEP_FLOOR_S:
                self.out.append(self.rule.finding(
                    self.ctx, node,
                    f"fixed {arg.value:g}s sleep inside a deadline loop overshoots small "
                    "caller timeouts; sleep min(step, remaining deadline)",
                    context=self.qual,
                ))


class _Finder(ast.NodeVisitor):
    def __init__(self, rule, ctx):
        self.rule = rule
        self.ctx = ctx
        self.out: list[Finding] = []
        self._qual: list[str] = []

    def _scoped(self, node):
        self._qual.append(node.name)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and _owns_deadline(node):
            lv = _LoopVisitor(self.rule, self.ctx, node, ".".join(self._qual))
            for stmt in node.body:
                lv.visit(stmt)
            self.out.extend(lv.out)
        self.generic_visit(node)
        self._qual.pop()

    visit_FunctionDef = _scoped
    visit_AsyncFunctionDef = _scoped
    visit_ClassDef = _scoped


class UnboundedPollInDeadlineLoop(Rule):
    id = "TPL006"
    name = "unbounded-poll-in-deadline-loop"
    summary = "recv/request/wait/sleep with no bound inside a loop owning a caller timeout"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        f = _Finder(self, ctx)
        f.visit(ctx.tree)
        yield from f.out
