"""TPL003: ``@remote`` function/class capturing non-serializable state.

Remote bodies are shipped as cloudpickle blobs (core direct plane:
``func_blobs``; head path: task specs). A nested ``@remote`` def whose
closure captures a lock, socket, file handle, subprocess, or live JAX
tracer pickles BY VALUE — the export either fails at submission time or,
worse, resurrects a dead handle on the worker. Same for hazard objects
baked into default arguments (evaluated once, at definition time, on the
driver). Pass such state in as an argument or construct it inside the
task.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ray_tpu.lint.engine import FileContext, Finding, Rule, dotted, has_decorator

# dotted-suffix patterns of constructors whose instances do not pickle
_HAZARD_SUFFIXES = {
    "Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore", "Event", "Barrier",
    "allocate_lock", "socket", "create_connection", "socketpair",
    "open", "popen", "Popen", "mmap", "connect", "TemporaryFile", "NamedTemporaryFile",
}
# jax trace-time objects leaking into a remote body
_HAZARD_EXACT = {"jax.core.new_main", "jax.make_jaxpr"}


def _hazard_call(expr: ast.AST) -> str | None:
    """Dotted name when ``expr`` constructs a known non-serializable."""
    if not isinstance(expr, ast.Call):
        return None
    name = dotted(expr.func)
    if name is None:
        return None
    if name in _HAZARD_EXACT or name.split(".")[-1] in _HAZARD_SUFFIXES:
        return name
    return None


def _local_bindings(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> dict[str, str]:
    """name -> hazard ctor dotted name, for simple assignments in ``fn``'s
    own body (nested defs excluded: their locals aren't this closure)."""
    out: dict[str, str] = {}
    for stmt in _walk_own(fn):
        if isinstance(stmt, ast.Assign):
            hz = _hazard_call(stmt.value)
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    if hz:
                        out[t.id] = hz
                    else:
                        out.pop(t.id, None)  # rebound to something benign
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                hz = _hazard_call(item.context_expr)
                if hz and isinstance(item.optional_vars, ast.Name):
                    out[item.optional_vars.id] = hz
    return out


def _walk_own(fn) -> Iterator[ast.stmt]:
    """Statements of ``fn`` excluding nested function/class bodies."""
    stack: list[ast.stmt] = list(fn.body)
    while stack:
        stmt = stack.pop()
        yield stmt
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            continue
        for fname in ("body", "orelse", "finalbody"):
            stack.extend(getattr(stmt, fname, None) or [])
        for handler in getattr(stmt, "handlers", None) or []:
            stack.extend(handler.body)


def _assigned_names(node: ast.AST) -> set[str]:
    out: set[str] = set()
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and isinstance(n.ctx, (ast.Store, ast.Del)):
            out.add(n.id)
        elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            out.add(n.name)
        elif isinstance(n, ast.arg):
            out.add(n.arg)
    return out


class RemoteCapturesUnserializable(Rule):
    id = "TPL003"
    name = "remote-captures-unserializable"
    summary = "@remote body closure-captures (or defaults to) a lock/socket/file/tracer that cannot pickle"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        # enclosing-function hazard bindings, maintained along a DFS
        yield from self._scan(ctx, ctx.tree, enclosing={}, qual=[])

    def _scan(self, ctx, node, enclosing: dict[str, str], qual: list[str]) -> Iterator[Finding]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual.append(child.name)
                if has_decorator(child, ("remote",)):
                    yield from self._check_remote_def(ctx, child, enclosing, ".".join(qual))
                merged = dict(enclosing)
                merged.update(_local_bindings(child))
                yield from self._scan(ctx, child, merged, qual)
                qual.pop()
            elif isinstance(child, ast.ClassDef):
                qual.append(child.name)
                if has_decorator(child, ("remote",)):
                    for meth in child.body:
                        if isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                            yield from self._check_remote_def(
                                ctx, meth, enclosing, ".".join(qual + [meth.name]), actor=True
                            )
                yield from self._scan(ctx, child, enclosing, qual)
                qual.pop()
            else:
                yield from self._scan(ctx, child, enclosing, qual)

    def _check_remote_def(self, ctx, fn, enclosing: dict[str, str], qual: str, actor: bool = False) -> Iterator[Finding]:
        # default arguments evaluated on the driver at def time
        args = fn.args
        for default in list(args.defaults) + [d for d in args.kw_defaults if d is not None]:
            hz = _hazard_call(default)
            if hz:
                yield self.finding(
                    ctx, default,
                    f"@remote default argument constructs {hz}() on the driver; "
                    "it cannot pickle to the worker — create it inside the task",
                    context=qual,
                )
        if not enclosing:
            return
        local = _assigned_names(fn)
        reported: set[str] = set()
        for n in ast.walk(fn):
            if (
                isinstance(n, ast.Name)
                and isinstance(n.ctx, ast.Load)
                and n.id in enclosing
                and n.id not in local
                and n.id not in reported
            ):
                reported.add(n.id)
                kind = "actor method" if actor else "remote function"
                yield self.finding(
                    ctx, n,
                    f"{kind} closure-captures '{n.id}' bound to {enclosing[n.id]}() in an "
                    "enclosing scope; cloudpickle ships it by value and it cannot pickle",
                    context=qual,
                )
