"""Rule registry: one module per rule, one instance per run.

The default registry spans the TPL (distributed-runtime AST), CCR
(concurrency-discipline) and ERR (fault-discipline) catalogs — all are
pure-AST passes over the same FileContext, so `all_rules()` runs them
together and the tree self-check / lint gate cover CCR and ERR
automatically. JXC (jaxpr) rules need tracing and stay behind ``--jax``.

``--select`` accepts ids, names, and retired alias ids (TPL004 selects
CCR006, TPL007 selects ERR001 — see engine.RULE_ALIASES).
"""

from __future__ import annotations

from ray_tpu.lint.concur.rules import CONCUR_RULES
from ray_tpu.lint.engine import Rule, canonical_rule
from ray_tpu.lint.fault.rules import FAULT_RULES
from ray_tpu.lint.rules.blocking_get import BlockingGetInActor
from ray_tpu.lint.rules.dropped_ref import DroppedObjectRef
from ray_tpu.lint.rules.jax_purity import JaxImpureJit
from ray_tpu.lint.rules.remote_capture import RemoteCapturesUnserializable
from ray_tpu.lint.rules.unbounded_poll import UnboundedPollInDeadlineLoop

_RULES = (
    BlockingGetInActor,
    DroppedObjectRef,
    RemoteCapturesUnserializable,
    JaxImpureJit,
    UnboundedPollInDeadlineLoop,
) + tuple(CONCUR_RULES) + tuple(FAULT_RULES)


def all_rules(select: set[str] | None = None) -> list[Rule]:
    rules = [cls() for cls in _RULES]
    if select:
        canon = {canonical_rule(s) for s in select}
        rules = [r for r in rules if r.id in canon or r.name in select]
    return rules


def rule_catalog() -> list[tuple[str, str, str]]:
    return [(cls.id, cls.name, cls.summary) for cls in _RULES]
