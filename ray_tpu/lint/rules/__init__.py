"""Rule registry: one module per rule, one instance per run."""

from __future__ import annotations

from ray_tpu.lint.engine import Rule
from ray_tpu.lint.rules.blocking_get import BlockingGetInActor
from ray_tpu.lint.rules.dropped_ref import DroppedObjectRef
from ray_tpu.lint.rules.jax_purity import JaxImpureJit
from ray_tpu.lint.rules.lock_order import LockOrderCycle
from ray_tpu.lint.rules.remote_capture import RemoteCapturesUnserializable
from ray_tpu.lint.rules.swallowed_conn_error import SwallowedConnError
from ray_tpu.lint.rules.unbounded_poll import UnboundedPollInDeadlineLoop

_RULES = (
    BlockingGetInActor,
    DroppedObjectRef,
    RemoteCapturesUnserializable,
    LockOrderCycle,
    JaxImpureJit,
    UnboundedPollInDeadlineLoop,
    SwallowedConnError,
)


def all_rules(select: set[str] | None = None) -> list[Rule]:
    rules = [cls() for cls in _RULES]
    if select:
        rules = [r for r in rules if r.id in select or r.name in select]
    return rules


def rule_catalog() -> list[tuple[str, str, str]]:
    return [(cls.id, cls.name, cls.summary) for cls in _RULES]
