"""TPL004: static lock-ordering cycle detection over ``with`` nesting.

The runtime lock_sanitizer builds this same ordering graph DYNAMICALLY —
but only over orderings the test run happens to execute. This rule builds
it lexically, per module: every ``with <lock>:`` whose body contains
another ``with <lock>:`` contributes an edge outer->inner (including
multi-item ``with a, b:``), and a cycle in the module graph is a
potential ABBA deadlock even if no test has interleaved the two paths
yet.

Lock expressions are Name/Attribute chains (never calls) whose final
segment looks lock-ish (lock/mutex/cond/cv/sem suffix). ``self.X`` inside
class C keys as ``C.X`` so methods of one class share nodes; other
prefixes keep their dotted spelling (``route.lock`` stays distinct from
``self._lock``).
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from ray_tpu.lint.engine import FileContext, Finding, Rule, dotted

_LOCKISH = re.compile(r"(?:^|_)(lock|mutex|mu|cond|cv|sem)$", re.IGNORECASE)


def _lock_key(expr: ast.AST, cls: str | None) -> str | None:
    name = dotted(expr)
    if name is None:
        return None
    if not _LOCKISH.search(name.split(".")[-1]):
        return None
    if cls and name.startswith("self."):
        return f"{cls}.{name[len('self.'):]}"
    return name


class _Visitor(ast.NodeVisitor):
    """Collect outer->inner edges with the location of the inner acquire."""

    def __init__(self):
        self.edges: dict[tuple[str, str], ast.AST] = {}
        self._held: list[str] = []
        self._cls: list[str] = []
        self._fn: list[str] = []

    def visit_ClassDef(self, node):
        self._cls.append(node.name)
        self.generic_visit(node)
        self._cls.pop()

    def _visit_fn(self, node):
        # a new function body starts with nothing lexically held: `with`
        # nesting does not cross call boundaries (that's the dynamic
        # sanitizer's job)
        held, self._held = self._held, []
        self._fn.append(node.name)
        self.generic_visit(node)
        self._fn.pop()
        self._held = held

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn

    def _visit_with(self, node):
        cls = self._cls[-1] if self._cls else None
        keys = []
        for item in node.items:
            k = _lock_key(item.context_expr, cls)
            if k is not None:
                keys.append(k)
                for outer in self._held + keys[:-1]:
                    if outer != k:
                        self.edges.setdefault((outer, k), item.context_expr)
        self._held.extend(keys)
        for stmt in node.body:
            self.visit(stmt)
        if keys:
            del self._held[-len(keys):]

    visit_With = _visit_with
    visit_AsyncWith = _visit_with

    @property
    def scope(self) -> str:
        return ".".join(self._cls + self._fn)


def _cycles(edges: dict[tuple[str, str], ast.AST]) -> list[list[str]]:
    graph: dict[str, set[str]] = {}
    for a, b in edges:
        graph.setdefault(a, set()).add(b)
    out: list[list[str]] = []
    seen_cycles: set[tuple[str, ...]] = set()

    def dfs(start: str, node: str, path: list[str], visited: set[str]):
        for nxt in sorted(graph.get(node, ())):
            if nxt == start:
                cyc = path[:]
                # canonicalize rotation so each cycle reports once
                i = cyc.index(min(cyc))
                canon = tuple(cyc[i:] + cyc[:i])
                if canon not in seen_cycles:
                    seen_cycles.add(canon)
                    out.append(list(canon))
            elif nxt not in visited and len(path) < 8:
                visited.add(nxt)
                dfs(start, nxt, path + [nxt], visited)
                visited.discard(nxt)

    for start in sorted(graph):
        dfs(start, start, [start], {start})
    return out


class LockOrderCycle(Rule):
    id = "TPL004"
    name = "lock-order-cycle"
    summary = "lexical `with` nesting acquires module locks in inconsistent order (potential ABBA deadlock)"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        v = _Visitor()
        v.visit(ctx.tree)
        for cyc in _cycles(v.edges):
            # anchor the report at the acquire site of the first inverted
            # edge; every consecutive cycle pair is an edge key by
            # construction, so index directly — drift should fail loudly,
            # not anchor the finding (and its suppression point) elsewhere
            a, b = cyc[0], cyc[1 % len(cyc)]
            node = v.edges[(a, b)]
            order = " -> ".join(cyc + [cyc[0]])
            yield self.finding(
                ctx, node,
                f"lock ordering cycle {order}: two paths acquire these locks in "
                "opposite order; pick one global order (see core/lock_sanitizer.py)",
                context="",
            )
