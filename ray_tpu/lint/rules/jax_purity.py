"""TPL005: Python side effects and host calls inside ``jit``/``pjit``
bodies, plus tracer leaks via ``global``/``nonlocal``.

A jitted function's Python body runs ONCE, at trace time; ``print``,
``time.time()``, host I/O, or stdlib/numpy RNG execute during tracing
and then never again — the compiled executable replays only the traced
ops, so the "side effect" silently disappears on the steps that matter
(and a wallclock read bakes a constant into the program). Writing a
traced value to a ``global``/``nonlocal`` leaks a tracer out of the
trace, which blows up later with the infamous leaked-tracer error.
MPMD-pipeline and Podracer-style designs (PAPERS.md) assume jit bodies
are pure; this rule keeps ours that way. Use ``jax.debug.print`` /
``jax.debug.callback`` and ``jax.random`` instead.
"""

from __future__ import annotations

import ast
from typing import Iterator

from ray_tpu.lint.engine import FileContext, Finding, Rule, decorator_names, dotted

_JIT_SUFFIXES = ("jit", "pjit")

# dotted names whose CALL inside a jit body is a trace-time side effect
_IMPURE_EXACT = {
    "print", "input", "breakpoint", "open",
    "time.time", "time.monotonic", "time.perf_counter", "time.sleep", "time.time_ns",
    "os.system", "os.popen", "os.read", "os.write", "os.remove", "os.unlink",
}
_IMPURE_PREFIXES = ("random.", "np.random.", "numpy.random.")


def _is_jitted(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> bool:
    return any(d.split(".")[-1] in _JIT_SUFFIXES for d in decorator_names(fn))


def _unwrap_partial(expr: ast.AST) -> ast.AST:
    """``partial(f, ...)``/``functools.partial(f, ...)`` -> ``f``."""
    if isinstance(expr, ast.Call) and (dotted(expr.func) or "").split(".")[-1] == "partial" and expr.args:
        return expr.args[0]
    return expr


def _call_form_jitted_names(tree: ast.Module) -> set[str]:
    """Function names wrapped by the CALL form: ``jax.jit(f)``,
    ``jit(partial(f, ...))`` — the dominant idiom in this codebase
    (model_runner builds prefill_fn/decode_fn this way) — and the
    variable-bound form ``step = partial(f, cfg=cfg); jax.jit(step)``
    (or a plain alias ``step = f``), resolved through one assignment.
    Binding collection is scope-insensitive by design: a false link only
    widens where purity is enforced. One walk collects both sides;
    bindings resolve afterwards, so assignment/jit ordering is free."""
    bindings: dict[str, str] = {}
    targets: set[str] = set()
    for n in ast.walk(tree):
        if isinstance(n, ast.Assign) and len(n.targets) == 1 and isinstance(n.targets[0], ast.Name):
            tname = dotted(_unwrap_partial(n.value))
            if tname is not None:
                bindings[n.targets[0].id] = tname.split(".")[-1]
        elif isinstance(n, ast.Call):
            fname = dotted(n.func)
            if fname is None or fname.split(".")[-1] not in _JIT_SUFFIXES or not n.args:
                continue
            tname = dotted(_unwrap_partial(n.args[0]))
            if tname is not None:
                targets.add(tname.split(".")[-1])
    return targets | {bindings[t] for t in targets if t in bindings}


def _impure_name(call: ast.Call) -> str | None:
    name = dotted(call.func)
    if name is None:
        return None
    if name in _IMPURE_EXACT or name.startswith(_IMPURE_PREFIXES):
        return name
    return None


class _BodyVisitor(ast.NodeVisitor):
    """Walk one jitted function body. Nested NON-jitted defs are included
    (they trace too when called); nested defs that _Finder will match on
    its own (jitted, or wrapped via the call form) are skipped so their
    findings report exactly once, under their own context."""

    def __init__(self, rule: "JaxImpureJit", ctx: FileContext, qual: str, call_form: set[str]):
        self.rule = rule
        self.ctx = ctx
        self.qual = qual
        self.call_form = call_form
        self.out: list[Finding] = []

    def _nested_def(self, node):
        if not (_is_jitted(node) or node.name in self.call_form):
            self.generic_visit(node)

    visit_FunctionDef = _nested_def
    visit_AsyncFunctionDef = _nested_def

    def visit_Call(self, node: ast.Call):
        name = _impure_name(node)
        if name is not None:
            fix = "jax.random with an explicit key" if "random" in name else "jax.debug.print/callback (or hoist out of jit)"
            self.out.append(self.rule.finding(
                self.ctx, node,
                f"{name}() inside a jit-compiled function runs only at trace time "
                f"(effect vanishes / value becomes a baked constant); use {fix}",
                context=self.qual,
            ))
        self.generic_visit(node)

    def visit_Global(self, node: ast.Global):
        self.out.append(self.rule.finding(
            self.ctx, node,
            f"`global {', '.join(node.names)}` inside a jit-compiled function can leak a "
            "tracer out of the trace; return the value instead",
            context=self.qual,
        ))

    def visit_Nonlocal(self, node: ast.Nonlocal):
        self.out.append(self.rule.finding(
            self.ctx, node,
            f"`nonlocal {', '.join(node.names)}` inside a jit-compiled function can leak a "
            "tracer out of the trace; return the value instead",
            context=self.qual,
        ))


class _Finder(ast.NodeVisitor):
    def __init__(self, rule, ctx, call_form: set[str]):
        self.rule = rule
        self.ctx = ctx
        self.call_form = call_form
        self.out: list[Finding] = []
        self._qual: list[str] = []

    def _scoped(self, node):
        self._qual.append(node.name)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) and (
            _is_jitted(node) or node.name in self.call_form
        ):
            bv = _BodyVisitor(self.rule, self.ctx, ".".join(self._qual), self.call_form)
            for stmt in node.body:
                bv.visit(stmt)
            self.out.extend(bv.out)
        self.generic_visit(node)
        self._qual.pop()

    visit_FunctionDef = _scoped
    visit_AsyncFunctionDef = _scoped
    visit_ClassDef = _scoped


class JaxImpureJit(Rule):
    id = "TPL005"
    name = "jax-impure-jit"
    summary = "side effect / host call / global write inside a jit-compiled function (trace-time-only execution)"

    def check(self, ctx: FileContext) -> Iterator[Finding]:
        f = _Finder(self, ctx, _call_form_jitted_names(ctx.tree))
        f.visit(ctx.tree)
        yield from f.out
