"""Serve configuration dataclasses.

Reference parity: python/ray/serve/config.py (AutoscalingConfig,
HTTPOptions) and _private/config.py (DeploymentConfig, ReplicaConfig) —
reduced to the knobs that matter on a TPU cluster: replica counts,
per-replica concurrency, autoscaling window, and the resources a replica
pins (including "TPU" for warm-engine replicas).
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class AutoscalingConfig:
    """Request-driven replica autoscaling (reference: serve/config.py
    AutoscalingConfig + _private/autoscaling_state.py decision logic)."""

    min_replicas: int = 1
    max_replicas: int = 10
    target_ongoing_requests: float = 2.0
    # smoothing: how long a scale decision must persist before acting
    upscale_delay_s: float = 0.0
    downscale_delay_s: float = 2.0
    metrics_interval_s: float = 0.2
    look_back_period_s: float = 2.0
    upscaling_factor: float = 1.0
    downscaling_factor: float = 1.0

    def __post_init__(self):
        if self.min_replicas < 0 or self.max_replicas < max(1, self.min_replicas):
            raise ValueError("need 0 <= min_replicas <= max_replicas, max >= 1")


@dataclass
class DeploymentConfig:
    num_replicas: int | None = 1
    max_ongoing_requests: int = 5
    autoscaling_config: AutoscalingConfig | None = None
    health_check_period_s: float = 1.0
    health_check_timeout_s: float = 10.0
    graceful_shutdown_timeout_s: float = 5.0
    user_config: dict | None = None

    def initial_target(self) -> int:
        if self.autoscaling_config is not None:
            return max(self.autoscaling_config.min_replicas, 1)
        return self.num_replicas or 1


@dataclass
class ReplicaConfig:
    """What each replica actor needs from the scheduler."""

    num_cpus: float = 1.0
    num_tpus: float = 0.0
    resources: dict = field(default_factory=dict)

    def to_actor_options(self) -> dict:
        opts = {"num_cpus": self.num_cpus}
        res = dict(self.resources)
        if self.num_tpus:
            res["TPU"] = self.num_tpus
        if res:
            opts["resources"] = res
        return opts


@dataclass
class HTTPOptions:
    host: str = "127.0.0.1"
    port: int = 8000
    # end-to-end per-request deadline; on expiry the proxy responds 504
    # and cancels the replica task (reference: request_timeout_s in
    # HTTPOptions, proxy timeout -> cancellation)
    request_timeout_s: float = 60.0
    # asyncio ingress (serve/_async_proxy.py): keep-alive + streaming
    # backpressure with O(1) threads, like the reference's uvicorn proxy
    # (serve/_private/proxy.py). False falls back to the stdlib
    # thread-per-connection server (serve/_proxy.py).
    async_proxy: bool = True
