"""DeploymentHandle: the client-side router.

Reference parity: serve/handle.py (DeploymentHandle / DeploymentResponse)
+ _private/request_router/pow_2_router.py:52 (power-of-two-choices
replica selection on queue length) + the handle-side queueing and metric
push from _private/router.py.

The handle caches the RUNNING replica set (refreshed from the controller
when its version changes or on a short interval), tracks its own
in-flight count per replica, and enforces max_ongoing_requests
client-side: requests beyond capacity queue here — queue depth is the
autoscaler's upscale signal, pushed via record_handle_metrics.
"""

from __future__ import annotations

import random
import threading
import time
import uuid
from collections import deque

import ray_tpu

_REFRESH_INTERVAL_S = 0.25


class DeploymentResponse:
    """Future for one request (reference: serve/handle.py
    DeploymentResponse — handles are ASYNC: .remote() never blocks the
    caller; requests beyond replica capacity queue inside the router and
    a dispatcher assigns them as slots free). `result()` blocks;
    `_to_object_ref()` unwraps for composition with ray_tpu.get/wait;
    `cancel()` propagates to the replica task and releases the slot."""

    def __init__(self, router, replica_id=None, ref=None):
        self._router = router
        self._replica_id = replica_id
        self._ref = ref
        self._error = None
        self._done = False
        self._cancelled = False
        self._bound = threading.Event()
        self._bind_cbs: list = []
        if ref is not None:
            self._bound.set()

    # -- dispatcher side --
    def _fire_bind_cbs(self):
        cbs, self._bind_cbs = self._bind_cbs, []
        for cb in cbs:
            try:
                cb()
            except Exception:
                pass

    def _bind(self, replica_id, ref):
        self._replica_id = replica_id
        self._ref = ref
        self._bound.set()
        self._fire_bind_cbs()

    def _fail(self, err: BaseException):
        self._error = err
        self._done = True
        self._bound.set()
        self._fire_bind_cbs()

    def _add_bind_callback(self, cb) -> bool:
        """Register cb() to run when the response binds or fails; returns
        False (without registering) if that already happened."""
        if self._bound.is_set():
            return False
        self._bind_cbs.append(cb)
        if self._bound.is_set() and cb in self._bind_cbs:
            # raced the bind: fire inline so the waiter can't be missed
            self._bind_cbs.remove(cb)
            return False
        return True

    def _settle(self):
        if not self._done:
            self._done = True
            if self._ref is not None:
                self._router._on_done(self._replica_id, self._ref)

    def _wait_bound(self, timeout_s: float | None):
        if not self._bound.wait(timeout=timeout_s):
            raise ray_tpu.exceptions.GetTimeoutError(
                f"request still queued for a replica after {timeout_s}s"
            )
        if self._error is not None:
            raise self._error

    def result(self, timeout_s: float | None = None):
        """A timeout raises but does NOT cancel (matching the reference:
        poll-with-timeout keeps the request running; call cancel() to
        abort)."""
        t0 = time.time()
        self._wait_bound(timeout_s)
        remaining = None if timeout_s is None else max(0.0, timeout_s - (time.time() - t0))
        try:
            v = ray_tpu.get(self._ref, timeout=remaining)
            self._settle()
            return v
        except ray_tpu.exceptions.GetTimeoutError:
            raise  # still in flight: slot stays held until done/cancelled
        except BaseException:
            self._settle()
            raise

    def cancel(self):
        """Best-effort cancellation (reference: DeploymentResponse.cancel):
        a queued request is dropped before dispatch; a dispatched replica
        task is cancelled; the router slot frees either way."""
        self._cancelled = True
        if self._ref is None:
            # not yet bound: the DISPATCHER settles/skips it (settling
            # here would mark _done and leak the slot it's about to claim)
            return
        try:
            ray_tpu.cancel(self._ref)
        except Exception:
            pass
        self._settle()

    def _to_object_ref(self, timeout_s: float | None = 60.0):
        self._wait_bound(timeout_s)
        return self._ref


class DeploymentResponseGenerator:
    """Streaming response: iterate per-item results as the replica yields
    them (reference: serve/handle.py DeploymentResponseGenerator over the
    streaming-generator protocol). ``item_timeout_s`` bounds each item
    fetch (GetTimeoutError aborts the stream and frees the slot)."""

    def __init__(self, router, replica_id, gen):
        self._router = router
        self._replica_id = replica_id
        self._gen = gen
        self._done = False
        self._exhausted = False
        self.item_timeout_s: float | None = None

    def __iter__(self):
        try:
            while True:
                try:
                    # bounds the wait for the NEXT item too, not just the
                    # fetch of a produced one
                    item_ref = self._gen.next_ref(timeout_s=self.item_timeout_s)
                except StopIteration:
                    self._exhausted = True
                    break
                yield ray_tpu.get(item_ref, timeout=self.item_timeout_s)
        finally:
            self._settle()

    def cancel(self):
        """Stop the replica-side generator (cooperative: it halts between
        yields) and release the router slot."""
        try:
            ray_tpu.cancel(ray_tpu.ObjectRef(self._gen.generator_id))
        except Exception:
            pass
        self._done = True
        self._router._on_done(self._replica_id, self._gen)

    def _settle(self):
        if not self._done:
            self._done = True
            if not self._exhausted:
                # abandoned/aborted mid-stream: stop the producer too
                try:
                    ray_tpu.cancel(ray_tpu.ObjectRef(self._gen.generator_id))
                except Exception:
                    pass
            self._router._on_done(self._replica_id, self._gen)

    def __del__(self):
        # backstop: a dropped, never-iterated stream must not leak its
        # replica slot forever (reap can't settle streaming entries)
        try:
            self._settle()
        except Exception:
            pass


class _Router:
    """Pow-2 replica choice + client-side admission control."""

    def __init__(self, controller, app_name: str, deployment: str):
        self._controller = controller
        self._app = app_name
        self._deployment = deployment
        self._handle_id = uuid.uuid4().hex[:8]
        self._lock = threading.Condition()
        self._version = -1
        self._replicas: list = []  # [(replica_id, actor)]
        self._max_ongoing = 1
        self._inflight: dict[str, int] = {}
        self._inflight_refs: dict = {}  # ref-id -> replica_id
        self._queued = 0
        self._pending_q: deque = deque()
        self._dispatcher = None
        self._last_refresh = 0.0
        self._last_push = 0.0
        self._last_push_ref = None  # latest metrics-push ref (see _push_metrics)
        from collections import OrderedDict

        # model_id -> last replica, LRU-capped so unbounded id
        # cardinality (per-user fine-tunes) can't grow forever; stale
        # replica ids are pruned on replica-set refresh
        self._model_affinity: OrderedDict = OrderedDict()

    # -- controller sync --

    def _refresh(self, force: bool = False):
        now = time.time()
        if not force and now - self._last_refresh < _REFRESH_INTERVAL_S:
            return
        self._last_refresh = now
        version, replicas, max_ongoing = ray_tpu.get(
            self._controller.get_replicas.remote(self._app, self._deployment, self._version)
        )
        with self._lock:
            if version != self._version:
                self._version = version
                self._replicas = replicas
                self._max_ongoing = max(1, max_ongoing)
                live = {rid for rid, _ in replicas}
                self._inflight = {rid: self._inflight.get(rid, 0) for rid in live}
                for mid in [m for m, rid in self._model_affinity.items() if rid not in live]:
                    del self._model_affinity[mid]
                self._lock.notify_all()
        self._push_metrics()

    def _push_metrics(self):
        now = time.time()
        if now - self._last_push < _REFRESH_INTERVAL_S / 2:
            return
        self._last_push = now
        with self._lock:
            demand = self._queued + sum(self._inflight.values())
        try:
            # keep the latest push's ref alive (tpulint TPL002): a dropped
            # ref frees the return immediately and loses the error channel;
            # holding the newest one lets a dead controller surface on the
            # next refresh instead of vanishing, and releases the previous
            # push's return as a side effect
            self._last_push_ref = self._controller.record_handle_metrics.remote(
                self._app, self._deployment, self._handle_id, demand
            )
        except Exception:
            pass

    # -- bookkeeping --

    def _on_done(self, replica_id, ref):
        with self._lock:
            if self._inflight_refs.pop(id(ref), None) is not None and replica_id in self._inflight:
                self._inflight[replica_id] = max(0, self._inflight[replica_id] - 1)
                self._lock.notify_all()
        self._push_metrics()

    def _waitable_refs(self):
        with self._lock:
            return [ref for ref, _rid, waitable in self._inflight_refs.values() if waitable]

    def _reap(self):
        """Settle finished in-flight refs without fetching their values
        (streaming entries settle through their generator's consumer)."""
        with self._lock:
            pending = [(k, ref, rid) for k, (ref, rid, waitable) in self._inflight_refs.items() if waitable]
        if not pending:
            return
        refs = [ref for _, ref, _ in pending]
        ready, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=0, fetch_local=False)
        ready_ids = {id(r) for r in ready}
        with self._lock:
            for key, ref, rid in pending:
                if id(ref) in ready_ids and key in self._inflight_refs:
                    del self._inflight_refs[key]
                    if rid in self._inflight:
                        self._inflight[rid] = max(0, self._inflight[rid] - 1)
            if ready_ids:
                self._lock.notify_all()

    # -- the router --

    def _pick_replica(self, model_id: str | None = None):
        """Two random choices, take the lower local in-flight count; None
        if every replica is at max_ongoing_requests. Multiplexed requests
        stick to the replica that last served their model (its LRU cache
        already holds the model — reference: model-aware routing in the
        multiplex-enabled router) whenever it has capacity."""
        candidates = [(rid, actor) for rid, actor in self._replicas if self._inflight.get(rid, 0) < self._max_ongoing]
        if not candidates:
            return None
        if model_id:
            sticky = self._model_affinity.get(model_id)
            for rid, actor in candidates:
                if rid == sticky:
                    return (rid, actor)
        if len(candidates) <= 2:
            picks = candidates
        else:
            picks = random.sample(candidates, 2)
        return min(picks, key=lambda c: self._inflight.get(c[0], 0))

    def submit(self, method_name: str, args: tuple, kwargs: dict, timeout_s: float | None = 60.0, stream: bool = False, multiplexed_model_id: str | None = None):
        """Non-streaming: ASYNC — enqueue and return an unbound
        DeploymentResponse immediately (reference handles never block the
        caller; queue depth drives the autoscaler). Streaming keeps the
        synchronous admission path (a generator needs its ref up front)."""
        if stream:
            rid, actor = self._admit(multiplexed_model_id, time.time() + timeout_s if timeout_s else None, timeout_s)
            return self._dispatch_stream(rid, actor, method_name, args, kwargs, multiplexed_model_id)
        resp = DeploymentResponse(self)
        deadline = time.time() + timeout_s if timeout_s else None
        with self._lock:
            self._pending_q.append((resp, method_name, args, kwargs, multiplexed_model_id, deadline, timeout_s))
            self._queued += 1
            self._ensure_dispatcher()
            self._lock.notify_all()
        self._push_metrics()
        return resp

    def _ensure_dispatcher(self):
        t = self._dispatcher
        if t is None or not t.is_alive():
            self._dispatcher = threading.Thread(target=self._dispatch_loop, daemon=True, name="rt-serve-dispatch")
            self._dispatcher.start()

    def _dispatch_loop(self):
        while True:
            with self._lock:
                if not self._pending_q:
                    # linger briefly for the next burst, then retire
                    self._lock.wait(timeout=5.0)
                    if not self._pending_q:
                        self._dispatcher = None
                        return
                item = self._pending_q.popleft()
            resp, method_name, args, kwargs, model_id, deadline, timeout_s = item
            if resp._cancelled:
                with self._lock:
                    self._queued -= 1
                resp._fail(ray_tpu.exceptions.RayTpuError("request cancelled before dispatch"))
                continue
            try:
                rid, actor = self._admit(model_id, deadline, timeout_s)
            except BaseException as e:  # noqa: BLE001
                with self._lock:
                    self._queued -= 1
                resp._fail(e)
                continue
            with self._lock:
                self._queued -= 1
            try:
                ref = actor.handle_request.remote(method_name, args, kwargs, model_id)
            except Exception as e:
                with self._lock:
                    if rid in self._inflight:
                        self._inflight[rid] = max(0, self._inflight[rid] - 1)
                resp._fail(e)
                continue
            with self._lock:
                self._inflight_refs[id(ref)] = (ref, rid, True)
            resp._bind(rid, ref)
            if resp._cancelled:
                resp.cancel()  # raced: propagate to the dispatched task
            self._push_metrics()

    def _admit(self, multiplexed_model_id, deadline, timeout_s):
        """Blocking admission: wait for a replica with a free slot and
        claim it. Runs on the dispatcher thread for async requests."""
        self._refresh(force=not self._replicas)
        while True:
            with self._lock:
                pick = self._pick_replica(multiplexed_model_id) if self._replicas else None
                if pick is not None:
                    rid, actor = pick
                    self._inflight[rid] = self._inflight.get(rid, 0) + 1
                    break
            # At capacity: settle any finished requests, re-sync the
            # replica set, then BLOCK on our in-flight completions
            # (the object store's waiter condition wakes us the moment
            # one finishes — no fixed-interval polling). With nothing
            # of ours in flight the replicas are saturated by other
            # handles: sleep one refresh beat for topology/metrics.
            self._reap()
            self._refresh(force=True)
            with self._lock:
                if self._pick_replica() is not None:
                    continue
            refs = self._waitable_refs()
            remaining = None if deadline is None else max(0.0, deadline - time.time())
            if refs:
                wait_t = _REFRESH_INTERVAL_S if remaining is None else min(remaining, _REFRESH_INTERVAL_S)
                ray_tpu.wait(refs, num_returns=1, timeout=wait_t, fetch_local=False)
                self._reap()
            else:
                time.sleep(0.02 if remaining is None else min(remaining, 0.02))
            if deadline and time.time() > deadline:
                # GetTimeoutError (a TimeoutError subclass): admission
                # timeouts now flow through result(), whose callers (e.g.
                # the proxy's 504 path) catch GetTimeoutError
                raise ray_tpu.exceptions.GetTimeoutError(
                    f"no replica of {self._app}/{self._deployment} accepted the request within {timeout_s}s"
                )
        if multiplexed_model_id:
            with self._lock:
                self._model_affinity[multiplexed_model_id] = rid
                self._model_affinity.move_to_end(multiplexed_model_id)
                while len(self._model_affinity) > 1024:
                    self._model_affinity.popitem(last=False)
        self._push_metrics()
        return rid, actor

    def _dispatch_stream(self, rid, actor, method_name, args, kwargs, multiplexed_model_id):
        try:
            ref = actor.handle_request_streaming.options(num_returns="streaming").remote(method_name, args, kwargs, multiplexed_model_id)
        except Exception:
            with self._lock:
                if rid in self._inflight:
                    self._inflight[rid] = max(0, self._inflight[rid] - 1)
            raise
        with self._lock:
            self._inflight_refs[id(ref)] = (ref, rid, False)
        return DeploymentResponseGenerator(self, rid, ref)


class DeploymentHandle:
    """User-facing handle; `.remote()` routes one request.

    h = serve.get_app_handle("app")
    ref = h.remote(x) / h.method.remote(x); ref.result()
    """

    def __init__(self, controller, app_name: str, deployment: str, method_name: str = "__call__", stream: bool = False, multiplexed_model_id: str | None = None):
        self._controller = controller
        self._app = app_name
        self._deployment = deployment
        self._method = method_name
        self._stream = stream
        self._model_id = multiplexed_model_id
        self._router = _Router(controller, app_name, deployment)

    def options(self, method_name: str | None = None, stream: bool | None = None, multiplexed_model_id: str | None = None):
        """`stream=True` makes `.remote()` return a
        DeploymentResponseGenerator; `multiplexed_model_id` tags the
        request for a @serve.multiplexed deployment and keeps it sticky
        to the replica holding that model (reference:
        handle.options(stream=..., multiplexed_model_id=...))."""
        h = DeploymentHandle(
            self._controller,
            self._app,
            self._deployment,
            method_name or self._method,
            stream=self._stream if stream is None else stream,
            multiplexed_model_id=self._model_id if multiplexed_model_id is None else multiplexed_model_id,
        )
        h._router = self._router  # share the router: one in-flight view
        return h

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return _MethodProxy(self, name)

    def remote(self, *args, **kwargs):
        return self._router.submit(self._method, args, kwargs, stream=self._stream, multiplexed_model_id=self._model_id)


class _MethodProxy:
    def __init__(self, handle: DeploymentHandle, method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs):
        return self._handle._router.submit(
            self._method, args, kwargs, stream=self._handle._stream, multiplexed_model_id=self._handle._model_id
        )
