"""gRPC ingress: route gRPC calls to application deployments.

Reference parity: the Serve gRPC proxy (serve/_private/proxy.py gRPCProxy
+ grpc_util.py) — the reference compiles user protos; here a
GenericRpcHandler serves one proto-less generic method so no protoc step
is needed (requests/responses are JSON bytes over standard gRPC/HTTP-2
framing):

    /ray_tpu.serve.Generic/Call
        request  b'{"application": ..., "method": ..., "args": [...],
                    "kwargs": {...}}'
        response b'{"result": ...}' | b'{"error": ...}'  (+ gRPC status)

Client side, any gRPC stack works; `grpc_call()` is the convenience
wrapper. Streaming deployments use /ray_tpu.serve.Generic/CallStreaming
(server-streaming: one JSON message per yielded item).
"""

from __future__ import annotations

import json
import threading

import ray_tpu
from ray_tpu.serve.handle import DeploymentHandle

_METHOD_UNARY = "/ray_tpu.serve.Generic/Call"
_METHOD_STREAM = "/ray_tpu.serve.Generic/CallStreaming"


class GrpcProxy:
    def __init__(self, controller, host: str = "127.0.0.1", port: int = 0):
        import grpc

        self._controller = controller
        self._handles: dict[str, DeploymentHandle] = {}
        self._lock = threading.Lock()
        proxy = self

        class Handler(grpc.GenericRpcHandler):
            def service(self, handler_call_details):
                if handler_call_details.method == _METHOD_UNARY:
                    return grpc.unary_unary_rpc_method_handler(
                        proxy._call, request_deserializer=None, response_serializer=None
                    )
                if handler_call_details.method == _METHOD_STREAM:
                    return grpc.unary_stream_rpc_method_handler(
                        proxy._call_streaming, request_deserializer=None, response_serializer=None
                    )
                return None

        from concurrent.futures import ThreadPoolExecutor

        self._server = grpc.server(ThreadPoolExecutor(max_workers=32))
        self._server.add_generic_rpc_handlers((Handler(),))
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        self.host = host
        self._server.start()

    def _handle(self, app: str) -> DeploymentHandle:
        with self._lock:
            h = self._handles.get(app)
        if h is not None:
            return h
        apps = ray_tpu.get(self._controller.list_applications.remote())
        if app not in apps:
            raise KeyError(f"no application {app!r} (have {sorted(apps)})")
        h = DeploymentHandle(self._controller, app, apps[app]["ingress"])
        with self._lock:
            self._handles[app] = h
        return h

    @staticmethod
    def _parse(request: bytes):
        body = json.loads(request or b"{}")
        return body["application"], body.get("method"), body.get("args") or [], body.get("kwargs") or {}

    def _drop_handle(self, app: str):
        # redeploys can change an app's ingress: invalidate on error like
        # the HTTP proxy's route refresh, so the next call rebuilds
        with self._lock:
            self._handles.pop(app, None)

    @staticmethod
    def _timeout(context) -> float:
        remaining = context.time_remaining()  # None without a client deadline
        return min(remaining, 3600.0) if remaining else 60.0

    def _call(self, request: bytes, context) -> bytes:
        import grpc

        app = None
        try:
            app, method, args, kwargs = self._parse(request)
            h = self._handle(app)
            if method:
                h = h.options(method_name=method)
            result = h.remote(*args, **kwargs).result(timeout_s=self._timeout(context))
            return json.dumps({"result": result}, default=str).encode()
        except Exception as e:  # noqa: BLE001
            if app:
                self._drop_handle(app)
            context.set_code(grpc.StatusCode.INTERNAL)
            context.set_details(repr(e))
            return json.dumps({"error": repr(e)}).encode()

    def _call_streaming(self, request: bytes, context):
        import grpc

        app = None
        try:
            app, method, args, kwargs = self._parse(request)
            h = self._handle(app).options(stream=True)
            if method:
                h = h.options(method_name=method)
            for item in h.remote(*args, **kwargs):
                yield json.dumps({"result": item}, default=str).encode()
        except Exception as e:  # noqa: BLE001
            if app:
                self._drop_handle(app)
            context.set_code(grpc.StatusCode.INTERNAL)
            context.set_details(repr(e))

    def stop(self):
        self._server.stop(grace=1.0)


def grpc_call(address: str, application: str, *args, method: str | None = None, timeout_s: float = 60.0, **kwargs):
    """Convenience unary client for the generic ingress."""
    import grpc

    with grpc.insecure_channel(address) as channel:
        fn = channel.unary_unary(_METHOD_UNARY, request_serializer=None, response_deserializer=None)
        payload = json.dumps({"application": application, "method": method, "args": list(args), "kwargs": kwargs}).encode()
        try:
            out = json.loads(fn(payload, timeout=timeout_s))
        except grpc.RpcError as e:
            raise RuntimeError(f"serve gRPC call failed: {e.details()}") from None
    if "error" in out:
        raise RuntimeError(out["error"])
    return out["result"]


def grpc_call_streaming(address: str, application: str, *args, method: str | None = None, timeout_s: float = 60.0, **kwargs):
    """Server-streaming client: yields each item the deployment yields."""
    import grpc

    with grpc.insecure_channel(address) as channel:
        fn = channel.unary_stream(_METHOD_STREAM, request_serializer=None, response_deserializer=None)
        payload = json.dumps({"application": application, "method": method, "args": list(args), "kwargs": kwargs}).encode()
        for msg in fn(payload, timeout=timeout_s):
            yield json.loads(msg)["result"]
