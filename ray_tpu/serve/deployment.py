"""@serve.deployment decorator, Deployment, and the bind() application
graph.

Reference parity: serve/deployment.py (Deployment, deployment decorator,
Application) and _private/build_app.py (graph -> per-deployment list with
handle injection). Binding another deployment's node as an init arg
becomes a DeploymentHandle at replica construction time, which is how
model-composition apps are built.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig, ReplicaConfig


@dataclass
class _HandleMarker:
    """Placeholder for a bound sub-deployment inside init args; resolved to
    a DeploymentHandle inside the replica (see _replica_init_resolver)."""

    app_name: str | None
    deployment: str


class Application:
    """A bound deployment graph node (reference: serve/deployment.py
    Application = Deployment.bind result)."""

    def __init__(self, deployment: "Deployment", args: tuple, kwargs: dict):
        self.deployment = deployment
        self.args = args
        self.kwargs = kwargs

    def _collect(self, out: dict):
        """DFS the graph, dedup by deployment name."""
        if self.deployment.name in out:
            return
        out[self.deployment.name] = self
        for a in list(self.args) + list(self.kwargs.values()):
            if isinstance(a, Application):
                a._collect(out)


@dataclass
class Deployment:
    func_or_class: object
    name: str
    config: DeploymentConfig = field(default_factory=DeploymentConfig)
    replica_config: ReplicaConfig = field(default_factory=ReplicaConfig)

    def bind(self, *args, **kwargs) -> Application:
        return Application(self, args, kwargs)

    def options(self, **kw) -> "Deployment":
        """Copy-with-overrides (reference Deployment.options)."""
        cfg_fields = {f for f in DeploymentConfig.__dataclass_fields__}
        rep_fields = {f for f in ReplicaConfig.__dataclass_fields__}
        cfg_kw = {k: v for k, v in kw.items() if k in cfg_fields}
        rep_kw = {k: v for k, v in kw.items() if k in rep_fields}
        other = {k: v for k, v in kw.items() if k not in cfg_fields and k not in rep_fields and k != "ray_actor_options"}
        name = other.pop("name", self.name)
        if "ray_actor_options" in kw:
            rao = kw["ray_actor_options"] or {}
            rep_kw.setdefault("num_cpus", rao.get("num_cpus", self.replica_config.num_cpus))
            rep_kw.setdefault("resources", rao.get("resources", dict(self.replica_config.resources)))
        if other:
            raise TypeError(f"unknown deployment options: {sorted(other)}")
        if isinstance(cfg_kw.get("autoscaling_config"), dict):
            cfg_kw["autoscaling_config"] = AutoscalingConfig(**cfg_kw["autoscaling_config"])
        if cfg_kw.get("num_replicas") == "auto":
            cfg_kw["num_replicas"] = None
            cfg_kw.setdefault("autoscaling_config", self.config.autoscaling_config or AutoscalingConfig())
        return Deployment(
            self.func_or_class,
            name,
            replace(self.config, **cfg_kw),
            replace(self.replica_config, **rep_kw),
        )


def deployment(_func_or_class=None, **kw):
    """@serve.deployment / @serve.deployment(num_replicas=..., ...)"""

    def make(target):
        d = Deployment(target, getattr(target, "__name__", "deployment"))
        return d.options(**kw) if kw else d

    if _func_or_class is not None:
        return make(_func_or_class)
    return make


def build_app_spec(app: Application, app_name: str) -> tuple[list[dict], str]:
    """Flatten a bound graph into the controller's deploy payload.

    Returns ([{name, cls_or_fn, init_args, init_kwargs, config,
    replica_config}], ingress_name). Application-valued args become
    _HandleMarker(app_name, dep_name).
    """
    nodes: dict[str, Application] = {}
    app._collect(nodes)

    def mark(v):
        return _HandleMarker(app_name, v.deployment.name) if isinstance(v, Application) else v

    specs = []
    for name, node in nodes.items():
        specs.append(
            {
                "name": name,
                "cls_or_fn": node.deployment.func_or_class,
                "init_args": tuple(mark(a) for a in node.args),
                "init_kwargs": {k: mark(v) for k, v in node.kwargs.items()},
                "config": node.deployment.config,
                "replica_config": node.deployment.replica_config,
            }
        )
    return specs, app.deployment.name
