"""HTTP proxy: routes HTTP requests to application ingress deployments.

Reference parity: serve/_private/proxy.py (per-node proxy with route
table from the controller) + proxy_router.py route matching. Here it is a
threaded stdlib HTTP server living in the driver (or any) process: routes
refresh from the controller's application table; each request becomes a
handle call with a Request object, longest-prefix route match.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import ray_tpu
from ray_tpu.serve.handle import DeploymentHandle


@dataclass
class Request:
    """Minimal HTTP request surface passed to ingress __call__ (the shape
    user code needs from starlette.requests.Request in the reference)."""

    method: str
    path: str
    query_params: dict = field(default_factory=dict)
    headers: dict = field(default_factory=dict)
    body: bytes = b""

    def json(self):
        return json.loads(self.body or b"null")

    @property
    def text(self) -> str:
        return self.body.decode()


class HTTPProxy:
    def __init__(self, controller, http_options):
        self._controller = controller
        self._opts = http_options
        self._routes: dict[str, DeploymentHandle] = {}
        self._routes_lock = threading.Lock()
        self._server: ThreadingHTTPServer | None = None
        self._stop = threading.Event()

    # -- route table --

    def _refresh_routes(self):
        apps = ray_tpu.get(self._controller.list_applications.remote())
        with self._routes_lock:
            known = set(self._routes)
            for app_name, info in apps.items():
                prefix = info.get("route_prefix") or "/"
                if prefix not in known:
                    self._routes[prefix] = DeploymentHandle(self._controller, app_name, info["ingress"])
            for prefix in known - {info.get("route_prefix") or "/" for info in apps.values()}:
                del self._routes[prefix]

    def _match(self, path: str) -> tuple[DeploymentHandle | None, str]:
        with self._routes_lock:
            best = None
            best_prefix = ""
            for prefix, handle in self._routes.items():
                p = prefix.rstrip("/")
                if (path == p or path.startswith(p + "/") or prefix == "/") and len(prefix) > len(best_prefix):
                    best, best_prefix = handle, prefix
            return best, best_prefix

    # -- server --

    def start(self):
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _handle(self):
                try:
                    proxy._refresh_routes()
                    parsed = urlparse(self.path)
                    handle, prefix = proxy._match(parsed.path)
                    if handle is None:
                        self._respond(404, {"error": f"no route for {parsed.path}"})
                        return
                    n = int(self.headers.get("Content-Length", 0) or 0)
                    body = self.rfile.read(n) if n else b""
                    sub_path = parsed.path[len(prefix.rstrip("/")):] or "/"
                    req = Request(
                        method=self.command,
                        path=sub_path,
                        query_params={k: v[0] for k, v in parse_qs(parsed.query).items()},
                        headers=dict(self.headers.items()),
                        body=body,
                    )
                    result = handle.remote(req).result(timeout_s=60.0)
                    self._respond(200, result)
                except Exception as e:  # noqa: BLE001
                    self._respond(500, {"error": repr(e)})

            def _respond(self, code: int, payload):
                if isinstance(payload, (bytes, bytearray)):
                    data, ctype = bytes(payload), "application/octet-stream"
                elif isinstance(payload, str):
                    data, ctype = payload.encode(), "text/plain"
                else:
                    data, ctype = json.dumps(payload).encode(), "application/json"
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            do_GET = do_POST = do_PUT = do_DELETE = _handle

        self._server = ThreadingHTTPServer((self._opts.host, self._opts.port), Handler)
        if self._opts.port == 0:
            self._opts.port = self._server.server_address[1]
        t = threading.Thread(target=self._server.serve_forever, name="serve-http-proxy", daemon=True)
        t.start()
        return self._opts.port

    def stop(self):
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None

    @property
    def port(self) -> int:
        return self._opts.port
