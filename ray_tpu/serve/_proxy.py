"""HTTP proxy: routes HTTP requests to application ingress deployments.

Reference parity: serve/_private/proxy.py (per-node proxy with route
table from the controller) + proxy_router.py route matching. Here it is a
threaded stdlib HTTP server living in the driver (or any) process: routes
refresh from the controller's application table; each request becomes a
handle call with a Request object, longest-prefix route match.
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import ray_tpu
from ray_tpu.serve.handle import DeploymentHandle


@dataclass
class Request:
    """Minimal HTTP request surface passed to ingress __call__ (the shape
    user code needs from starlette.requests.Request in the reference)."""

    method: str
    path: str
    query_params: dict = field(default_factory=dict)
    headers: dict = field(default_factory=dict)
    body: bytes = b""

    def json(self):
        return json.loads(self.body or b"null")

    @property
    def text(self) -> str:
        return self.body.decode()


class RouteTableMixin:
    """Controller route table shared by the sync and async proxies: cached
    refresh (one controller round-trip per interval; forced refreshes on
    route miss are rate-limited too, or a 404 scanner would reintroduce a
    controller RTT per request) + longest-prefix match."""

    def _init_routes(self, controller):
        self._controller = controller
        self._routes: dict[str, DeploymentHandle] = {}
        self._routes_lock = threading.Lock()
        self._routes_at = 0.0

    def _refresh_routes(self, force: bool = False):
        now = time.time()
        interval = 0.25 if force else 1.0
        if now - self._routes_at < interval:
            return
        self._routes_at = now
        apps = ray_tpu.get(self._controller.list_applications.remote())
        with self._routes_lock:
            known = set(self._routes)
            for app_name, info in apps.items():
                prefix = info.get("route_prefix") or "/"
                if prefix not in known:
                    self._routes[prefix] = DeploymentHandle(self._controller, app_name, info["ingress"])
            for prefix in known - {info.get("route_prefix") or "/" for info in apps.values()}:
                del self._routes[prefix]

    def _match(self, path: str) -> tuple[DeploymentHandle | None, str]:
        with self._routes_lock:
            best = None
            best_prefix = ""
            for prefix, handle in self._routes.items():
                p = prefix.rstrip("/")
                if (path == p or path.startswith(p + "/") or prefix == "/") and len(prefix) > len(best_prefix):
                    best, best_prefix = handle, prefix
            return best, best_prefix


class HTTPProxy(RouteTableMixin):
    def __init__(self, controller, http_options):
        self._init_routes(controller)
        self._opts = http_options
        self._server: ThreadingHTTPServer | None = None
        self._stop = threading.Event()

    # -- server --

    def start(self):
        proxy = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # quiet
                pass

            def _handle(self):
                try:
                    proxy._refresh_routes()
                    parsed = urlparse(self.path)
                    handle, prefix = proxy._match(parsed.path)
                    if handle is None:
                        # route may be new: force one refresh before 404ing
                        proxy._refresh_routes(force=True)
                        handle, prefix = proxy._match(parsed.path)
                    if handle is None:
                        self._respond(404, {"error": f"no route for {parsed.path}"})
                        return
                    n = int(self.headers.get("Content-Length", 0) or 0)
                    body = self.rfile.read(n) if n else b""
                    sub_path = parsed.path[len(prefix.rstrip("/")):] or "/"
                    req = Request(
                        method=self.command,
                        path=sub_path,
                        query_params={k: v[0] for k, v in parse_qs(parsed.query).items()},
                        headers=dict(self.headers.items()),
                        body=body,
                    )
                    timeout = proxy._opts.request_timeout_s
                    if self._wants_stream(req):
                        self._stream(handle.options(stream=True).remote(req), timeout)
                        return
                    resp = handle.remote(req)
                    try:
                        result = resp.result(timeout_s=timeout)
                    except ray_tpu.exceptions.GetTimeoutError:
                        resp.cancel()  # deadline is final at the proxy
                        self._respond(504, {"error": f"request exceeded {timeout}s"})
                        return
                    self._respond(200, result)
                except Exception as e:  # noqa: BLE001
                    from ray_tpu.serve.overload import http_error_of

                    mapped = http_error_of(e)
                    if mapped is not None:
                        # typed serving errors carry their own status:
                        # OverloadedError/ReplicaDrainingError -> 429 with
                        # a retry-after hint instead of a generic 500
                        self._respond(mapped[0], mapped[1])
                        return
                    import traceback as _tb

                    self._respond(500, {"error": repr(e), "trace": _tb.format_exc()})

            def _wants_stream(self, req: Request) -> bool:
                accept = req.headers.get("Accept", "") or req.headers.get("accept", "")
                if "text/event-stream" in accept or req.headers.get("X-Serve-Stream") == "1":
                    return True
                # OpenAI-style bodies signal streaming in JSON, not
                # headers — but only sniff on the OpenAI endpoints, so an
                # unrelated deployment whose schema has a top-level
                # "stream" field keeps its unary framing
                if req.path.endswith(("/completions", "/chat/completions")) and req.body[:1] == b"{" and b'"stream"' in req.body:
                    try:
                        return req.json().get("stream") is True
                    except ValueError:
                        return False
                return False

            def _stream(self, gen, timeout):
                """Chunked transfer: one chunk per yielded item (reference:
                proxy streaming of StreamingResponse bodies). The FIRST
                item is fetched before the 200 header commits, so an
                ingress that sheds (OverloadedError) or errors at
                admission still gets its typed status (429 + retry-after)
                instead of a fake 200. Errors and timeouts AFTER the 200
                header abort the connection WITHOUT the chunked
                terminator — a truncated stream is the only honest error
                signal once streaming began; a clean terminator would
                make partial output look complete (and a second response
                would desync HTTP/1.1 keep-alive)."""
                import itertools

                def cancel():
                    # every failure path must abort the admitted
                    # generation (the unary path's resp.cancel()), or the
                    # abandoned request holds a batch slot generating
                    # tokens nobody consumes — inflating host_load()
                    # occupancy and shedding real traffic
                    try:
                        gen.cancel()
                    except Exception:  # noqa: BLE001
                        pass

                deadline = time.time() + timeout if timeout else None
                it = iter(gen)
                exhausted = False
                try:
                    if deadline is not None:
                        gen.item_timeout_s = max(deadline - time.time(), 0.01)
                    first = next(it)
                    it = itertools.chain([first], it)
                except StopIteration:
                    exhausted = True
                except ray_tpu.exceptions.GetTimeoutError:
                    # same deadline classification as the unary path: a
                    # first-token timeout is a 504, not a server fault
                    cancel()
                    self._respond(504, {"error": f"request exceeded {timeout}s"})
                    return
                except Exception as e:  # noqa: BLE001
                    from ray_tpu.serve.overload import http_error_of

                    cancel()
                    mapped = http_error_of(e)
                    if mapped is not None:
                        self._respond(mapped[0], mapped[1])
                        return
                    import traceback as _tb

                    self._respond(500, {"error": repr(e), "trace": _tb.format_exc()})
                    return
                self.send_response(200)
                self.send_header("Content-Type", "application/octet-stream")
                self.send_header("Transfer-Encoding", "chunked")
                self.end_headers()

                def chunk(data: bytes):
                    self.wfile.write(f"{len(data):X}\r\n".encode() + data + b"\r\n")
                    self.wfile.flush()

                clean = exhausted  # an empty stream terminates cleanly
                try:
                    while not exhausted:
                        if deadline is not None:
                            remaining = deadline - time.time()
                            if remaining <= 0:
                                break  # unclean abort below
                            gen.item_timeout_s = remaining
                        try:
                            item = next(it)
                        except StopIteration:
                            clean = True
                            break
                        if isinstance(item, (bytes, bytearray)):
                            data = bytes(item)
                        elif isinstance(item, str):
                            data = item.encode()
                        else:
                            data = (json.dumps(item) + "\n").encode()
                        chunk(data)
                except Exception:  # noqa: BLE001  (incl. GetTimeoutError)
                    clean = False
                finally:
                    if clean:
                        try:
                            self.wfile.write(b"0\r\n\r\n")
                            self.wfile.flush()
                        except OSError:
                            pass
                    else:
                        cancel()  # post-header abort: same slot-leak rule
                        self.close_connection = True

            def _respond(self, code: int, payload):
                if isinstance(payload, (bytes, bytearray)):
                    data, ctype = bytes(payload), "application/octet-stream"
                elif isinstance(payload, str):
                    data, ctype = payload.encode(), "text/plain"
                else:
                    data, ctype = json.dumps(payload).encode(), "application/json"
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                if code == 429 and isinstance(payload, dict) and payload.get("retry_after_s"):
                    # the STANDARD backoff header: off-the-shelf clients /
                    # load balancers honor Retry-After, not our body field
                    import math

                    self.send_header("Retry-After", str(max(1, math.ceil(float(payload["retry_after_s"])))))
                self.end_headers()
                self.wfile.write(data)

            do_GET = do_POST = do_PUT = do_DELETE = _handle

        self._server = ThreadingHTTPServer((self._opts.host, self._opts.port), Handler)
        if self._opts.port == 0:
            self._opts.port = self._server.server_address[1]
        t = threading.Thread(target=self._server.serve_forever, name="serve-http-proxy", daemon=True)
        t.start()
        return self._opts.port

    def stop(self):
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None

    @property
    def port(self) -> int:
        return self._opts.port
