"""Asyncio HTTP ingress: keep-alive, streaming backpressure, O(1) threads.

Reference parity: python/ray/serve/_private/proxy.py:1 — the reference
runs uvicorn/starlette end-to-end async; this is the same shape on a raw
asyncio.start_server loop (no third-party server in the image): an HTTP/1.1
parser, longest-prefix route match against the controller's application
table, unary requests awaited via seal callbacks (zero blocked threads),
and streaming responses chunk-written with `await drain()` so a slow
client backpressures its own stream instead of buffering unboundedly.
The event loop runs on one daemon thread; handle SUBMISSION (router
locks, admission) runs in a small executor; WAITING costs no threads
(serve/_async_bridge.py).

The stdlib ThreadingHTTPServer proxy (_proxy.py) remains available via
HTTPOptions(async_proxy=False).
"""

from __future__ import annotations

import asyncio
import json
import threading
import time
from urllib.parse import parse_qs, urlparse

import ray_tpu
from ray_tpu.serve._async_bridge import aiter_stream, result_async
from ray_tpu.serve._proxy import Request, RouteTableMixin

_MAX_HEADER = 64 << 10
_MAX_BODY = 512 << 20


class _HTTPError(Exception):
    def __init__(self, code: int, msg: str):
        super().__init__(msg)
        self.code = code


class AsyncHTTPProxy(RouteTableMixin):
    def __init__(self, controller, http_options):
        self._init_routes(controller)
        self._opts = http_options
        self._loop: asyncio.AbstractEventLoop | None = None
        self._server: asyncio.AbstractServer | None = None
        self._thread: threading.Thread | None = None
        self._started = threading.Event()

    # -- lifecycle --
    def start(self) -> int:
        self._thread = threading.Thread(target=self._run_loop, name="serve-async-proxy", daemon=True)
        self._thread.start()
        if not self._started.wait(timeout=30.0):
            raise RuntimeError("async proxy failed to start")
        return self._opts.port

    def _run_loop(self):
        self._loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self._loop)
        self._loop.run_until_complete(self._serve())

    async def _serve(self):
        self._server = await asyncio.start_server(self._handle_conn, self._opts.host, self._opts.port)
        if self._opts.port == 0:
            self._opts.port = self._server.sockets[0].getsockname()[1]
        self._started.set()
        async with self._server:
            try:
                await self._server.serve_forever()
            except asyncio.CancelledError:
                pass

    def stop(self):
        if self._loop is not None:

            def _shutdown():
                for task in asyncio.all_tasks(self._loop):
                    task.cancel()

            self._loop.call_soon_threadsafe(_shutdown)
            if self._thread is not None:
                self._thread.join(timeout=10.0)
            self._loop = None
            self._server = None

    @property
    def port(self) -> int:
        return self._opts.port

    # -- connection handling --
    async def _handle_conn(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        try:
            while True:  # HTTP/1.1 keep-alive: many requests per connection
                try:
                    req, keep_alive = await self._read_request(reader)
                except (asyncio.IncompleteReadError, ConnectionError):
                    return
                except _HTTPError as e:
                    await self._respond(writer, e.code, {"error": str(e)}, close=True)
                    return
                if req is None:
                    return
                try:
                    close = await self._dispatch(req, writer) or not keep_alive
                except (ConnectionError, asyncio.CancelledError):
                    return
                except Exception as e:  # noqa: BLE001
                    from ray_tpu.serve.overload import http_error_of

                    mapped = http_error_of(e)  # typed 429s keep their status
                    try:
                        if mapped is not None:
                            await self._respond(writer, mapped[0], mapped[1])
                        else:
                            await self._respond(writer, 500, {"error": repr(e)})
                    except ConnectionError:
                        return
                    close = not keep_alive
                if close:
                    return
        finally:
            try:
                writer.close()
            except Exception:
                pass

    async def _read_request(self, reader) -> tuple[Request | None, bool]:
        try:
            head = await reader.readuntil(b"\r\n\r\n")
        except asyncio.LimitOverrunError:
            raise _HTTPError(431, "headers too large") from None
        except asyncio.IncompleteReadError as e:
            if not e.partial:
                return None, False  # clean keep-alive close
            raise
        if len(head) > _MAX_HEADER:
            raise _HTTPError(431, "headers too large")
        lines = head.decode("latin-1").split("\r\n")
        try:
            method, target, version = lines[0].split(" ", 2)
        except ValueError:
            raise _HTTPError(400, "bad request line") from None
        headers = {}
        lower = {}  # case-insensitive view for request framing
        for ln in lines[1:]:
            if not ln:
                continue
            k, _, v = ln.partition(":")
            headers[k.strip()] = v.strip()
            lower[k.strip().lower()] = v.strip()
        n = int(lower.get("content-length", 0) or 0)
        if n > _MAX_BODY:
            raise _HTTPError(413, "body too large")
        body = await reader.readexactly(n) if n else b""
        keep_alive = lower.get("connection", "").lower() != "close" and version != "HTTP/1.0"
        parsed = urlparse(target)
        req = Request(
            method=method,
            path=parsed.path,
            query_params={k: v[0] for k, v in parse_qs(parsed.query).items()},
            headers=headers,
            body=body,
        )
        return req, keep_alive

    def _wants_stream(self, req: Request) -> bool:
        # header NAMES are case-insensitive (RFC 9110); Request preserves
        # wire case for user code, so scan case-insensitively here
        lower = {k.lower(): v for k, v in req.headers.items()}
        if "text/event-stream" in lower.get("accept", "") or lower.get("x-serve-stream") == "1":
            return True
        if req.path.endswith(("/completions", "/chat/completions")) and req.body[:1] == b"{" and b'"stream"' in req.body:
            try:
                return req.json().get("stream") is True
            except ValueError:
                return False
        return False

    async def _dispatch(self, req: Request, writer) -> bool:
        """Returns True if the connection must close (aborted stream)."""
        loop = asyncio.get_running_loop()
        await loop.run_in_executor(None, self._refresh_routes)
        handle, prefix = self._match(req.path)
        if handle is None:
            await loop.run_in_executor(None, self._refresh_routes, True)
            handle, prefix = self._match(req.path)
        if handle is None:
            await self._respond(writer, 404, {"error": f"no route for {req.path}"})
            return False
        req.path = req.path[len(prefix.rstrip("/")):] or "/"
        timeout = self._opts.request_timeout_s
        if self._wants_stream(req):
            gen = await loop.run_in_executor(None, handle.options(stream=True).remote, req)
            return await self._stream(writer, gen, timeout)
        resp = await loop.run_in_executor(None, handle.remote, req)
        try:
            result = await result_async(resp, timeout_s=timeout)
        except ray_tpu.exceptions.GetTimeoutError:
            resp.cancel()
            await self._respond(writer, 504, {"error": f"request exceeded {timeout}s"})
            return False
        await self._respond(writer, 200, result)
        return False

    async def _stream(self, writer, gen, timeout) -> bool:
        """Chunked streaming with drain() backpressure. The FIRST item is
        fetched before the 200 header commits, so a shed (OverloadedError
        -> 429 + retry-after) or admission error keeps its typed status.
        As in the sync proxy, an error AFTER the 200 header aborts
        WITHOUT the chunked terminator — truncation is the only honest
        mid-stream error."""
        # the whole-request deadline starts at stream OPEN (matching the
        # sync proxy): TTFT spends from the same budget as the body
        deadline = time.time() + timeout if timeout else None
        ait = aiter_stream(gen, item_timeout_s=timeout).__aiter__()
        exhausted = False
        first = None
        have_first = False
        try:
            first = await ait.__anext__()
            have_first = True
        except StopAsyncIteration:
            exhausted = True
        except asyncio.CancelledError:
            raise
        except ray_tpu.exceptions.GetTimeoutError:
            # same deadline classification as the unary path: a
            # first-token timeout is a 504, not a server fault. The
            # remote generation was already admitted — cancel it, as the
            # mid-stream abort path does, or the abandoned request holds
            # a slot generating tokens nobody consumes.
            self._cancel_stream(gen)
            try:
                await self._respond(writer, 504, {"error": f"request exceeded {timeout}s"})
            except ConnectionError:
                return True
            return False
        except Exception as e:  # noqa: BLE001
            from ray_tpu.serve.overload import http_error_of

            self._cancel_stream(gen)
            mapped = http_error_of(e)
            try:
                if mapped is not None:
                    await self._respond(writer, mapped[0], mapped[1])
                else:
                    await self._respond(writer, 500, {"error": repr(e)})
            except ConnectionError:
                return True
            return False
        writer.write(
            b"HTTP/1.1 200 OK\r\nContent-Type: application/octet-stream\r\nTransfer-Encoding: chunked\r\n\r\n"
        )
        clean = exhausted  # an empty stream terminates cleanly

        def _encode(item) -> bytes:
            if isinstance(item, (bytes, bytearray)):
                return bytes(item)
            if isinstance(item, str):
                return item.encode()
            return (json.dumps(item) + "\n").encode()

        try:
            while not exhausted:
                if deadline is not None and time.time() > deadline:
                    break  # unclean abort below
                if have_first:
                    item, have_first = first, False
                else:
                    try:
                        item = await ait.__anext__()
                    except StopAsyncIteration:
                        clean = True
                        break
                data = _encode(item)
                writer.write(f"{len(data):X}\r\n".encode() + data + b"\r\n")
                await writer.drain()  # slow client backpressures HERE
        except (Exception, asyncio.CancelledError):  # noqa: BLE001
            clean = False
        if clean:
            try:
                writer.write(b"0\r\n\r\n")
                await writer.drain()
            except ConnectionError:
                return True
            return False
        self._cancel_stream(gen)
        return True  # aborted: close so the client sees truncation

    @staticmethod
    def _cancel_stream(gen) -> None:
        """Abort the remote generation behind an abandoned stream (every
        failure path — pre- and post-header — must cancel, or the request
        keeps its slot generating tokens nobody consumes)."""
        try:
            gen.cancel()
        except Exception:  # noqa: BLE001
            pass

    async def _respond(self, writer, code: int, payload, close: bool = False):
        if isinstance(payload, (bytes, bytearray)):
            data, ctype = bytes(payload), "application/octet-stream"
        elif isinstance(payload, str):
            data, ctype = payload.encode(), "text/plain"
        else:
            data, ctype = json.dumps(payload).encode(), "application/json"
        reason = {200: "OK", 404: "Not Found", 413: "Payload Too Large", 429: "Too Many Requests", 431: "Headers Too Large", 500: "Internal Server Error", 504: "Gateway Timeout"}.get(code, "")
        extra = b""
        if code == 429 and isinstance(payload, dict) and payload.get("retry_after_s"):
            # the STANDARD backoff header: off-the-shelf clients / load
            # balancers honor Retry-After, not our body field
            import math

            extra = f"Retry-After: {max(1, math.ceil(float(payload['retry_after_s'])))}\r\n".encode()
        conn = b"Connection: close\r\n" if close else b""
        writer.write(
            f"HTTP/1.1 {code} {reason}\r\nContent-Type: {ctype}\r\nContent-Length: {len(data)}\r\n".encode()
            + extra
            + conn
            + b"\r\n"
            + data
        )
        await writer.drain()
