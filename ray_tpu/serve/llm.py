"""Serve x LLM: batched inference deployments over the TPU engine.

Reference parity: python/ray/llm/_internal/serve/ (LLMServer deployment
wrapping a vLLM engine, build_llm_deployment/build_openai_app) — rebuilt
on ray_tpu.llm.LLMEngine: one engine per replica, a background stepping
thread drives continuous batching across ALL concurrent requests hitting
the replica (each request blocks on its own completion event while the
engine interleaves every active sequence per decode step), autoscaling
rides Serve's request-metric autoscaler (BASELINE config #4: batched
Llama inference on autoscaling TPU replicas).

    from ray_tpu import serve
    from ray_tpu.serve.llm import LLMConfig, build_llm_deployment

    app = build_llm_deployment(LLMConfig(model_config=LlamaConfig(...)))
    handle = serve.run(app, name="llm")
    out = handle.generate.remote([1, 2, 3], {"max_tokens": 16}).result()
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field


@dataclass
class LLMConfig:
    model_config: object = None  # ray_tpu.models.llama.LlamaConfig
    params: object = None  # optional pretrained pytree
    engine_kwargs: dict = field(default_factory=dict)  # max_num_seqs, ...
    num_replicas: int = 1
    # -1 = auto: tensor_parallel_size chips when tp > 1, else none.
    # Explicit 0 opts out (CPU-mesh testing).
    num_tpus_per_replica: float = -1
    autoscaling_config: object = None  # serve.AutoscalingConfig
    max_ongoing_requests: int = 32
    # TP-sharded engine: the replica builds a tp mesh over this many of
    # its visible devices and the engine compiles SPMD over it (reference
    # capability: vllm_models.py:215-228 tensor_parallel_size). Also sets
    # the replica's TPU resource request when num_tpus_per_replica is 0.
    tensor_parallel_size: int = 1


class LLMServer:
    """Deployment class: continuous batching across concurrent callers."""

    def __init__(self, llm_config: LLMConfig):
        from ray_tpu.llm import LLMEngine

        cfg = llm_config.model_config
        if cfg is None:
            from ray_tpu.models.llama import LlamaConfig

            cfg = LlamaConfig.tiny(dtype="float32")
        engine_kwargs = dict(llm_config.engine_kwargs)
        tp = int(llm_config.tensor_parallel_size or 1)
        if tp > 1 and "mesh" not in engine_kwargs:
            import jax

            from ray_tpu.parallel.mesh import create_mesh

            devices = jax.devices()
            if len(devices) < tp:
                raise ValueError(f"tensor_parallel_size={tp} but replica sees {len(devices)} devices")
            engine_kwargs["mesh"] = create_mesh(tp=tp, devices=devices[:tp])
        self.engine = LLMEngine(cfg, params=llm_config.params, **engine_kwargs)
        self._done: dict[str, object] = {}  # request_id -> RequestOutput
        self._events: dict[str, threading.Event] = {}
        self._lock = threading.Lock()
        self._stopped = False
        self._stepper_error: str | None = None
        self._work = threading.Event()
        self._stepper = threading.Thread(target=self._step_loop, daemon=True, name="llm-stepper")
        self._stepper.start()

    def check_health(self):
        """Serve health hook: a dead stepper means a dead engine."""
        if self._stepper_error is not None:
            raise RuntimeError(f"llm stepper died:\n{self._stepper_error}")
        return True

    # -- engine pump: one thread advances every active sequence together --
    def _step_loop(self):
        while not self._stopped:
            if not self.engine.has_unfinished():
                # block until a request arrives (no idle busy-poll)
                self._work.wait(timeout=1.0)
                self._work.clear()
                continue
            try:
                outs = self.engine.step()
            except Exception:  # noqa: BLE001
                # a dying stepper must not wedge the replica silently:
                # fail every waiter now and mark the replica unhealthy so
                # the controller replaces it
                import traceback

                self._stepper_error = traceback.format_exc()
                with self._lock:
                    events = list(self._events.values())
                    self._events.clear()
                for ev in events:
                    ev.set()
                return
            for out in outs:
                if out.finished:
                    with self._lock:
                        self._done[out.request_id] = out
                        ev = self._events.get(out.request_id)
                    if ev is not None:
                        ev.set()

    # -- request paths --
    def generate(self, prompt_token_ids, sampling_params: dict | None = None, timeout_s: float = 300.0) -> dict:
        """Blocking generation; many concurrent calls batch in the engine."""
        from ray_tpu.llm import SamplingParams

        if self._stepper_error is not None:
            raise RuntimeError(f"llm stepper died:\n{self._stepper_error}")
        params = SamplingParams(**(sampling_params or {}))
        ev = threading.Event()
        rid = self.engine.add_request(list(prompt_token_ids), params)
        with self._lock:
            if rid in self._done:  # finished before we registered (tiny prompts)
                ev.set()
            self._events[rid] = ev
        self._work.set()
        if not ev.wait(timeout_s):
            self.engine.abort_request(rid)
            with self._lock:  # reap bookkeeping (completion may have raced)
                self._events.pop(rid, None)
                self._done.pop(rid, None)
            raise TimeoutError(f"generation {rid} timed out after {timeout_s}s")
        with self._lock:
            self._events.pop(rid, None)
            out = self._done.pop(rid, None)
        if out is None:
            raise RuntimeError(f"llm stepper died:\n{self._stepper_error or 'unknown'}")
        return {
            "request_id": out.request_id,
            "prompt_token_ids": out.prompt_token_ids,
            "token_ids": out.token_ids,
            "finish_reason": out.finish_reason,
        }

    def batch_stats(self) -> dict:
        return {"running": self.engine.num_running, "waiting": self.engine.num_waiting}

    def __call__(self, request):
        """HTTP entry: POST {"prompt_token_ids": [...], "sampling_params": {...}}."""
        body = request.json() if hasattr(request, "json") else dict(request)
        return self.generate(body["prompt_token_ids"], body.get("sampling_params"))

    def __del__(self):
        self._stopped = True


def build_llm_deployment(llm_config: LLMConfig, *, name: str = "LLMServer"):
    """-> a Serve Application running LLMServer replicas (reference:
    llm/_internal/serve/builders.py build_llm_deployment)."""
    from ray_tpu import serve

    opts = {
        "name": name,
        "max_ongoing_requests": llm_config.max_ongoing_requests,
        # engine construction + first prefill/decode compiles take tens of
        # seconds; don't let the controller shoot the replica meanwhile
        "health_check_timeout_s": 180.0,
        "health_check_period_s": 2.0,
    }
    if llm_config.autoscaling_config is not None:
        opts["autoscaling_config"] = llm_config.autoscaling_config
    else:
        opts["num_replicas"] = llm_config.num_replicas
    num_tpus = llm_config.num_tpus_per_replica
    if num_tpus < 0:
        # auto: a TP replica gang-reserves its chips (reference: vLLM
        # replicas request tensor_parallel_size accelerators via their PG)
        num_tpus = float(llm_config.tensor_parallel_size) if llm_config.tensor_parallel_size > 1 else 0.0
    if num_tpus:
        opts["num_tpus"] = num_tpus  # ReplicaConfig field
    deployment = serve.deployment(**opts)(LLMServer)
    return deployment.bind(llm_config)
