"""Serve x LLM: batched inference deployments over the TPU engine.

Reference parity: python/ray/llm/_internal/serve/ (LLMServer deployment
wrapping a vLLM engine, build_llm_deployment/build_openai_app) — rebuilt
on ray_tpu.llm.LLMEngine: one engine per replica, a background stepping
thread drives continuous batching across ALL concurrent requests hitting
the replica (each request blocks on its own completion event while the
engine interleaves every active sequence per decode step), autoscaling
rides Serve's request-metric autoscaler (BASELINE config #4: batched
Llama inference on autoscaling TPU replicas).

    from ray_tpu import serve
    from ray_tpu.serve.llm import LLMConfig, build_llm_deployment

    app = build_llm_deployment(LLMConfig(model_config=LlamaConfig(...)))
    handle = serve.run(app, name="llm")
    out = handle.generate.remote([1, 2, 3], {"max_tokens": 16}).result()
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field

from ray_tpu import chaos
from ray_tpu.exceptions import GetTimeoutError
from ray_tpu.serve.overload import (
    AdmissionController,
    OverloadedError,  # noqa: F401 (re-export: the ingress's typed 429)
    ReplicaDrainingError,  # noqa: F401 (re-export)
    StepperDiedError,
)


@dataclass
class LLMConfig:
    model_config: object = None  # ray_tpu.models.llama.LlamaConfig
    params: object = None  # optional pretrained pytree
    engine_kwargs: dict = field(default_factory=dict)  # max_num_seqs, ...
    # OpenAI-style API: model name echoed in responses, and an optional
    # tokenizer with encode(str)->list[int] / decode(list[int])->str
    # (e.g. transformers AutoTokenizer); without one, string prompts are
    # rejected and token-id prompts/completions pass through.
    model_id: str = "ray_tpu-llama"
    tokenizer: object = None
    num_replicas: int = 1
    # -1 = auto: tensor_parallel_size chips when tp > 1, else none.
    # Explicit 0 opts out (CPU-mesh testing).
    num_tpus_per_replica: float = -1
    autoscaling_config: object = None  # serve.AutoscalingConfig
    max_ongoing_requests: int = 32
    # TP-sharded engine: the replica builds a tp mesh over this many of
    # its visible devices and the engine compiles SPMD over it (reference
    # capability: vllm_models.py:215-228 tensor_parallel_size). Also sets
    # the replica's TPU resource request when num_tpus_per_replica is 0.
    tensor_parallel_size: int = 1
    # speculative decoding (llm.spec.SpecConfig): forwarded to the engine
    # unless engine_kwargs already carries its own "speculative"
    speculative: object = None
    # pre-warm at replica construction: compile the serving hot path
    # (smallest prefill bucket + fused decode; prefill+extract on prefill
    # replicas) BEFORE the replica reports healthy, so deployment
    # spin-up — not the first request — pays the XLA compiles, in
    # parallel across replicas (BENCH_scale.json: disagg_spinup)
    prewarm: bool = True
    # admission control / load shedding at the replica ingress
    # (serve/overload.AdmissionConfig). None = the default caps; pass
    # AdmissionConfig(enabled=False) to admit unconditionally (the
    # overload bench's baseline arm). Past the caps, generate() raises
    # OverloadedError (HTTP 429 + retry-after) with the lowest request
    # class (SamplingParams.priority / body "priority") shed first.
    admission: object = None
    # default evacuation deadline for a chaos-/signal-delivered
    # preemption notice (LLMServer.preempt -> drain(mode="migrate")):
    # checkpoints of in-flight decode state must publish inside it;
    # stragglers abort typed (the SIGTERM-with-deadline contract)
    preempt_deadline_s: float = 5.0


class LLMServer:
    """Deployment class: continuous batching across concurrent callers."""

    # stamped into every telemetry series/span this replica emits (the
    # model/replica/stage tag triple; llm/telemetry.py)
    telemetry_stage = "serve"

    def __init__(self, llm_config: LLMConfig):
        from ray_tpu.llm import LLMEngine
        from ray_tpu.llm.telemetry import default_tags

        cfg = llm_config.model_config
        if cfg is None:
            from ray_tpu.models.llama import LlamaConfig

            cfg = LlamaConfig.tiny(dtype="float32")
        engine_kwargs = dict(llm_config.engine_kwargs)
        engine_kwargs.setdefault(
            "telemetry_tags", default_tags(self.telemetry_stage, model=llm_config.model_id)
        )
        if llm_config.speculative is not None:
            engine_kwargs.setdefault("speculative", llm_config.speculative)
        tp = int(llm_config.tensor_parallel_size or 1)
        if tp > 1 and "mesh" not in engine_kwargs:
            import jax

            from ray_tpu.parallel.mesh import create_mesh

            devices = jax.devices()
            if len(devices) < tp:
                raise ValueError(f"tensor_parallel_size={tp} but replica sees {len(devices)} devices")
            engine_kwargs["mesh"] = create_mesh(tp=tp, devices=devices[:tp])
        self.engine = LLMEngine(cfg, params=llm_config.params, **engine_kwargs)
        self._done: dict[str, object] = {}  # request_id -> RequestOutput
        self._events: dict[str, threading.Event] = {}
        # per-request typed failures delivered OUT of band of the step
        # loop (live migration hands each evacuated waiter its own
        # RequestMigratedError; the abort fallback its 429)
        self._errors: dict[str, BaseException] = {}
        self._lock = threading.Lock()
        self._stopped = False
        # drain idempotency: a controller retrying its shutdown hook (or
        # a preemption racing a manual drain) re-observes the first
        # drain's outcome instead of double-releasing owned state
        self._drain_lock = threading.Lock()
        self._drain_result: dict | None = None
        self._preempt_deadline_s = float(llm_config.preempt_deadline_s)
        self._stepper_error: str | None = None
        self._work = threading.Event()
        # bounded admission at this replica's ingress (serve/overload.py):
        # past the caps generate() sheds with a typed OverloadedError
        # instead of joining an unbounded queue — overload degrades shed
        # rate and queue wait, never in-flight decode ITL
        self._admission = AdmissionController(self.engine, llm_config.admission)
        if llm_config.prewarm:
            # BEFORE the stepping thread exists: engine.generate drives
            # its own loop and would race a concurrent stepper
            self._prewarm()
        self._stepper = threading.Thread(target=self._step_loop, daemon=True, name="llm-stepper")
        self._stepper.start()

    def _prewarm(self):
        """Compile the replica's hot programs at construction (smallest
        prefill bucket, fused decode step, sampling; speculative programs
        when enabled): the controller marks the replica RUNNING only
        after __init__, so a warmed fleet serves its first real request
        at steady-state latency instead of burying it under compiles."""
        self._prewarm_compile()
        self._seed_admission_emas()

    def _prewarm_compile(self):
        from ray_tpu.llm import SamplingParams

        self.engine.generate([1, 2, 3], SamplingParams(max_tokens=2, temperature=0.0))

    def _prewarm_probe(self):
        """One WARM tiny request (all programs already compiled) — the
        admission plane's steady-state yardstick."""
        from ray_tpu.llm import SamplingParams

        self.engine.generate([1, 2, 3], SamplingParams(max_tokens=2, temperature=0.0))

    def _seed_admission_emas(self):
        """Admission cold-start fix: the compile-heavy prewarm request
        reads as a multi-second service time (the est-queue-wait cap
        would shed everything until the EMA decays), and with no samples
        at all the EMAs sit at 0 (the cap is vacuous until the first
        finish). Reset both EMAs and re-measure ONE warm probe request,
        so the first real admission decision sees steady-state numbers —
        the probe's on_finish/on_emit stamps seed service and ITL
        directly (an EMA at 0 adopts its first sample)."""
        tel = getattr(self.engine, "_tel", None)
        if tel is None:
            return
        tel.itl_ema_s = 0.0
        tel.service_ema_s = 0.0
        self._prewarm_probe()

    def check_health(self):
        """Serve health hook: a dead stepper means a dead engine."""
        if self._stepper_error is not None:
            raise StepperDiedError(f"llm stepper died:\n{self._stepper_error}")
        return True

    # -- engine pump: one thread advances every active sequence together --
    def _step_loop(self):
        while not self._stopped:
            if not self.engine.has_unfinished():
                # an IDLE replica must keep its cluster-index lease alive
                # (engine.step never runs here, so its heartbeat hook
                # never fires): a silent replica's published prefixes
                # would stop matching after ttl_s and, once pruned, could
                # never re-register
                plane = getattr(self.engine, "_kv_plane", None)
                if plane is not None:
                    plane.maybe_heartbeat()
                # block until a request arrives (no idle busy-poll)
                self._work.wait(timeout=1.0)
                self._work.clear()
                continue
            try:
                # preemption notice (SIGTERM-with-deadline, chaos-shaped):
                # a DROP rule delivers the notice and the replica starts
                # evacuating via live migration from a side thread — the
                # stepper keeps ticking until drain() stops it, exactly
                # like a real signal handler; a raises rule escalates to
                # SIGKILL semantics (stepper dies, no grace). Inert
                # one-flag check unarmed.
                if not chaos.apply("serve.preempt"):
                    if not self._admission.draining:
                        threading.Thread(
                            target=self.preempt, daemon=True, name="llm-preempt"
                        ).start()
                # chaos plane: a delay rule stalls this replica's decode
                # ticks, a drop rule skips them (a stall without sleeping
                # inside the rule), a raises rule kills the stepper
                # exactly like a replica crash (waiters fail, health check
                # trips, routers fail over). Inert one-flag check unarmed.
                if not chaos.apply("serve.step"):
                    time.sleep(0.005)  # dropped tick: yield, don't spin
                    continue
                outs = self.engine.step()
            except Exception:  # noqa: BLE001
                # a dying stepper must not wedge the replica silently:
                # fail every waiter now and mark the replica unhealthy so
                # the controller replaces it
                import traceback

                self._fail_all_waiters(traceback.format_exc())
                return
            self._deliver_outputs(outs)

    def _fail_all_waiters(self, reason: str) -> None:
        """The ONE failure sweep for a stepper that will never step again
        (death, drain's broken-engine path, shutdown with work in
        flight): record the reason, wake every blocked _await_finished
        waiter, and push sentinels into streaming consumers' queues —
        they block on their queues, not events, and re-check
        _stepper_error on waking."""
        if self._stepper_error is None:
            self._stepper_error = reason
        with self._lock:
            events = list(self._events.values())
            self._events.clear()
        for ev in events:
            ev.set()
        with self.engine._lock:
            streams = [st.out_queue for st in self.engine._requests.values() if st.out_queue is not None]
        for q in streams:
            q.put(None)

    def _deliver_outputs(self, outs):
        """Publish finished outputs to their blocked waiters (the stepper's
        delivery half; drain() reuses it for the post-abort cleanup step)."""
        for out in outs:
            # streamed requests deliver through their out_queue; putting
            # them in _done would leak (no collector ever pops them)
            if out.finished and not out.streamed:
                with self._lock:
                    self._done[out.request_id] = out
                    ev = self._events.get(out.request_id)
                if ev is not None:
                    ev.set()

    def _check_alive(self):
        """Ingress guard: a dead stepper surfaces its error; a cleanly
        STOPPED stepper (shutdown() is public API — benches, drain,
        teardown) must fail fast with a typed failover signal instead of
        admitting work nothing will ever step (the waiter would ride out
        its whole timeout)."""
        if self._stopped:
            # a STOPPED replica is a deliberate lifecycle state, checked
            # BEFORE the stepper error (shutdown's waiter sweep records
            # one — it must not reclassify the typed failover signal as
            # a server fault). Drained replicas defer to the admission
            # controller so the shed is counted with its real class; a
            # bare shutdown has no drain state and fails fast here.
            if not self._admission.draining:
                raise ReplicaDrainingError(
                    "replica is shut down (stepper stopped)", retry_after_s=1.0
                )
            return
        if self._stepper_error is not None:
            raise StepperDiedError(f"llm stepper died:\n{self._stepper_error}")

    # -- request paths --
    def generate(self, prompt_token_ids, sampling_params: dict | None = None, timeout_s: float = 300.0) -> dict:
        """Blocking generation; many concurrent calls batch in the engine."""
        from ray_tpu.llm import SamplingParams

        self._check_alive()
        params = SamplingParams(**(sampling_params or {}))
        # admission control: raises OverloadedError (429 + retry-after)
        # past the caps, lowest request class first; ReplicaDrainingError
        # while drain() is finishing in-flight work
        self._admission.check(params.priority)
        rid = self._admit(list(prompt_token_ids), params)
        out = self._await_finished(rid, timeout_s)
        return {
            "request_id": out.request_id,
            "prompt_token_ids": out.prompt_token_ids,
            "token_ids": out.token_ids,
            "finish_reason": out.finish_reason,
        }

    def _await_finished(self, rid: str, timeout_s: float):
        """Block until the stepping thread finishes request ``rid`` and
        return its RequestOutput (shared by generate, the disaggregated
        handoff path, and the prefill replica's handoff wait)."""
        ev = threading.Event()
        with self._lock:
            # finished (tiny prompts) or failed/migrated before we
            # registered: don't wait for an event nobody will set
            if rid in self._done or rid in self._errors:
                ev.set()
            self._events[rid] = ev
        self._work.set()
        if self._stopped and not ev.is_set():
            # raced a shutdown between the ingress check and admission:
            # nothing will ever step this request — fail fast with the
            # failover signal instead of riding out timeout_s
            self.engine.abort_request(rid)
            with self._lock:
                self._events.pop(rid, None)
                out = self._done.pop(rid, None)
            if out is not None:
                return out
            raise ReplicaDrainingError(
                "replica shut down while admitting", retry_after_s=1.0
            )
        if not ev.wait(timeout_s):
            self.engine.abort_request(rid)
            with self._lock:  # reap bookkeeping (completion may have raced)
                self._events.pop(rid, None)
                self._done.pop(rid, None)
                self._errors.pop(rid, None)
            raise TimeoutError(f"generation {rid} timed out after {timeout_s}s")
        with self._lock:
            self._events.pop(rid, None)
            err = self._errors.pop(rid, None)
            out = self._done.pop(rid, None)
        if err is not None:
            # per-request typed failure (live migration's resume signal,
            # the preemption abort fallback) — not a server fault
            raise err
        if out is None:
            raise StepperDiedError(f"llm stepper died:\n{self._stepper_error or 'unknown'}")
        return out

    def _fail_waiter(self, rid: str, exc: BaseException) -> None:
        """Deliver ONE request's typed failure to its blocked waiter
        (the per-request flavor of _fail_all_waiters: live migration
        hands each evacuated request its own RequestMigratedError)."""
        with self._lock:
            self._errors[rid] = exc
            ev = self._events.get(rid)
        if ev is not None:
            ev.set()

    def _admit(self, prompt_token_ids, params) -> str:
        """Admission seam: monolithic replicas prefill locally; the
        disaggregated DecodeServer overrides this to source KV from a
        prefill replica."""
        return self.engine.add_request(prompt_token_ids, params)

    def batch_stats(self) -> dict:
        return {"running": self.engine.num_running, "waiting": self.engine.num_waiting}

    def prefix_cache_stats(self) -> dict:
        return self.engine.prefix_cache_stats()

    def spec_stats(self) -> dict:
        """Speculative decoding counters (empty when speculation is off):
        acceptance rate, proposed/accepted totals, mean tokens per verify
        round, per-request effective k."""
        return self.engine.spec_stats()

    def kv_cache_stats(self) -> dict:
        """KV-cache accounting: dtype/layout, bytes per token (int8
        scales included), allocated vs occupied HBM, slot/page occupancy."""
        return self.engine.kv_cache_stats()

    def telemetry(self) -> dict:
        """Flight-recorder snapshot (llm/telemetry.py): per-step ring,
        finished-request TTFT/ITL/queue-wait lifecycle records, recompile
        sentinel counts, and this replica's model/replica/stage tags."""
        return self.engine.telemetry()

    def overload_stats(self) -> dict:
        """Admission-control counters: admitted, shed by cause and by
        request class, live queue-wait estimate, drain state."""
        return self._admission.stats()

    def __call__(self, request):
        """HTTP entry: POST {"prompt_token_ids": [...], "sampling_params": {...}}."""
        body = request.json() if hasattr(request, "json") else dict(request)
        return self.generate(body["prompt_token_ids"], body.get("sampling_params"))

    # -- replica lifecycle -------------------------------------------------
    def _stop_stepper(self) -> None:
        """Set the stop flag AND wake the idle wait, then join: exit is
        immediate instead of riding out the 1 s idle tick. No waiter
        sweep — drain()'s timeout path stops the stepper first and then
        delivers the aborted finals itself."""
        self._stopped = True
        self._work.set()
        st = getattr(self, "_stepper", None)
        if st is not None and st.is_alive() and st is not threading.current_thread():
            st.join(timeout=5.0)

    def shutdown(self) -> None:
        """Stop the stepper thread promptly. Used by benches/tests,
        drain(), and __del__. Waiters still blocked on in-flight work
        fail fast (nothing will ever step them) instead of riding out
        their timeouts; drain() settles in-flight work FIRST, so its
        final shutdown finds none."""
        self._stop_stepper()
        with self._lock:
            pending = bool(self._events)
        if pending or self.engine.has_unfinished():
            self._fail_all_waiters("replica shut down (stepper stopped) with requests in flight")

    def drain(self, timeout_s: float = 30.0, mode: str = "abort") -> dict:
        """Graceful drain, the replica's half of fleet failover:

        1. stop admitting — new requests shed with ReplicaDrainingError
           (a 429 subclass: routers fail over, clients back off);
        2. settle in-flight work. ``mode="abort"`` (default) finishes it
           bounded by ``timeout_s`` and aborts whatever is left past the
           deadline; ``mode="migrate"`` EVACUATES instead: the stepper
           stops, every in-flight request's live decode state is
           checkpointed and published over the object plane
           (llm/migrate.py), and each waiter gets a typed
           RequestMigratedError carrying (meta, ref) — the routers'
           resume-on-peer leg splices it with ZERO recomputed tokens.
           Whatever cannot checkpoint (streams, prefill stubs, sampled
           cold requests, post-deadline stragglers) aborts with a typed
           429 so the router re-prefills — the degradation order is
           migrate -> re-prefill -> typed error;
        3. release owned resources while the process is still healthy:
           stashed handoff blocks drop, and a cluster-KV-plane replica
           unregisters every published prefix from the index and frees
           the owned blocks (route dies before the bytes). Published
           live_state checkpoints are deliberately NOT freed — a peer
           must still fetch them; they die with this process (a fetch
           losing that race sees MigrationLostError, and the leak
           backstop reclaims never-fetched ones);
        4. stop the stepper (shutdown()).

        Idempotent: a second drain (controller retrying its shutdown
        hook, a preemption racing a manual drain) returns the first
        drain's record with ``repeated=True`` — never a double-free.
        Serve's graceful teardown calls this through the replica's
        shutdown hook; it is also directly callable for planned
        rebalancing. Returns what was drained/migrated."""
        if mode not in ("abort", "migrate"):
            raise ValueError(f"drain mode must be 'abort' or 'migrate', got {mode!r}")  # tpulint: disable=ERR002 — operator-API argument validation, never client-visible
        with self._drain_lock:
            if self._drain_result is not None:
                return dict(self._drain_result, repeated=True)
            res = self._drain_once(timeout_s, mode)
            self._drain_result = res
            return dict(res)

    def _drain_once(self, timeout_s: float, mode: str) -> dict:
        from ray_tpu.serve.overload import wait_for_drain

        deadline = time.time() + timeout_s
        self._admission.drain()
        migrated: list = []
        aborted = 0
        if mode == "migrate":
            # evacuation: stop the stepper FIRST (quiescent engine under
            # us), then checkpoint + publish every in-flight request and
            # hand its waiter the typed resume signal
            self._stop_stepper()
            migrated, aborted = self._migrate_inflight(deadline)
            finished = aborted == 0
        else:
            finished = wait_for_drain(self, timeout_s=timeout_s)
            if not finished:
                # deadline passed with work still in flight: stop the stepper
                # FIRST (joins any in-progress step — no concurrent stepping),
                # abort what's left, then run ONE cleanup step ourselves so
                # the aborted finals publish through the normal path and
                # blocked waiters wake NOW instead of riding out their own
                # timeouts (abort outputs only surface via the next step)
                self._stop_stepper()
                try:
                    with self.engine._lock:
                        rids = [rid for rid, st in self.engine._requests.items() if not st.finished]
                    for rid in rids:
                        aborted += bool(self.engine.abort_request(rid))
                    self._deliver_outputs(self.engine.step())
                except Exception:  # noqa: BLE001 — drain is BEST-EFFORT: the
                    # likeliest reason the deadline passed is a broken engine,
                    # and the resource release below must still run; fail any
                    # still-blocked waiters exactly like the stepper-death path
                    import traceback

                    self._fail_all_waiters(traceback.format_exc())
        released = self.engine.release_handoffs()
        plane = getattr(self.engine, "_kv_plane", None)
        unregistered = plane.shutdown() if plane is not None else 0
        self._admission.drained()
        self.shutdown()
        return {
            "drained": True,
            "mode": mode,
            "inflight_finished": finished,
            "aborted": aborted,
            "migrated": migrated,
            "handoffs_released": released,
            "kvplane_keys_unregistered": unregistered,
        }

    def _migrate_inflight(self, deadline: float) -> tuple:
        """Checkpoint + publish every in-flight request (waiters get the
        typed resume signal); abort with a typed 429 is the per-request
        fallback. The stepper is already stopped — the engine is
        quiescent under us. Returns ([{request_id, meta, ref}], n_aborted)."""
        from ray_tpu.llm import migrate as _mig

        eng = self.engine
        with eng._lock:
            rids = [rid for rid, st in eng._requests.items() if not st.finished]
        migrated: list = []
        aborted = 0
        for rid in rids:
            err = None
            if time.time() < deadline:
                try:
                    state = eng.checkpoint_request(rid)
                    meta, ref = _mig.publish(state)
                    err = _mig.RequestMigratedError(rid, meta, ref)
                except Exception:  # tpulint: disable=ERR001 — noqa: BLE001 — checkpoint/publish failure degrades to the abort leg below; the request still terminates typed
                    err = None
            if err is not None:
                migrated.append({"request_id": rid, "meta": err.migration_meta,
                                 "ref": err.migration_ref})
                eng.finish_migrated(rid)
                self._fail_waiter(rid, err)
            else:
                aborted += 1
                tel = getattr(eng, "_tel", None)
                if tel is not None:
                    tel.on_migration("aborted")
                eng.abort_request(rid)
                # a typed 429 (not a partial result): the router's
                # re-prefill leg replays the whole request on a peer
                self._fail_waiter(rid, ReplicaDrainingError(
                    "replica preempted before this request could checkpoint; "
                    "re-prefill on a peer", retry_after_s=1.0,
                ))
        # one cleanup step publishes the evacuated finals through the
        # normal path (streams get their sentinels); waiters already woke
        # with their typed errors
        try:
            self._deliver_outputs(self.engine.step())
        except Exception:  # noqa: BLE001 — best-effort, like the abort drain
            import traceback

            self._fail_all_waiters(traceback.format_exc())
        return migrated, aborted

    def preempt(self, deadline_s: float | None = None) -> dict:
        """Preemption notice: the SIGTERM-with-deadline a TPU fleet's
        preemptible capacity actually delivers. Evacuates via
        drain(mode="migrate") bounded by the deadline
        (LLMConfig.preempt_deadline_s by default); driven by the
        ``serve.preempt`` chaos site in tests and callable directly by a
        real signal handler."""
        d = self._preempt_deadline_s if deadline_s is None else float(deadline_s)
        return self.drain(timeout_s=d, mode="migrate")

    def resume_from_migration(self, meta: dict, ref, sampling_params: dict | None = None,
                              timeout_s: float = 300.0) -> dict:
        """Peer-side splice of a migrated request (llm/migrate.py): fetch
        the published checkpoint (bounded retry — a dead owner raises
        MigrationLostError, the router's signal to re-prefill), restore
        it into this replica's engine, and decode to completion. The
        returned token_ids are the FULL stream (pre-splice + new): the
        client sees one uninterrupted result."""
        from ray_tpu.llm import migrate as _mig

        self._check_alive()
        # shed BEFORE borrowing the checkpoint: an overloaded peer must
        # bounce the router onward without touching the block ("no peer
        # admits them" spends the router's RetryBudget into the abort leg)
        self._admission.check(int((sampling_params or {}).get("priority", 0)))
        state = _mig.fetch(ref, meta)
        rid = self.engine.restore_request(state)
        self._work.set()
        out = self._await_finished(rid, timeout_s)
        return {
            "request_id": out.request_id,
            "prompt_token_ids": out.prompt_token_ids,
            "token_ids": out.token_ids,
            "finish_reason": out.finish_reason,
        }

    def suspend_request(self, request_id: str, publish: bool = True) -> dict:
        """Tiered conversation KV (llm/engine.suspend_request): spill one
        in-flight conversation's KV out of HBM to host DRAM + the object
        plane, freeing its slot/pages for active traffic. The request
        finishes locally with reason "suspended" (a blocked ``generate``
        waiter sees that reason, mirroring the migration signal);
        ``resume_suspended`` continues it later with zero recomputed
        tokens. Raises MigrationError when the request cannot suspend —
        the conversation is then untouched and still running."""
        self._check_alive()
        res = self.engine.suspend_request(request_id, publish=publish)
        self._work.set()  # let the stepper reap the retirement promptly
        return res

    def resume_suspended(self, request_id: str, timeout_s: float = 300.0) -> dict:
        """Re-admit a suspended conversation (scatter-in, no re-prefill)
        and block until it finishes — the resume twin of ``generate``."""
        self._check_alive()
        rid = self.engine.resume_suspended(request_id)
        self._work.set()
        out = self._await_finished(rid, timeout_s)
        return {
            "request_id": out.request_id,
            "prompt_token_ids": out.prompt_token_ids,
            "token_ids": out.token_ids,
            "finish_reason": out.finish_reason,
        }

    def suspended_requests(self) -> list:
        return self.engine.suspended_requests()

    def __del__(self):
        try:
            self.shutdown()
        except Exception:  # noqa: BLE001 — interpreter teardown
            pass


class OpenAIServer(LLMServer):
    """OpenAI-compatible request surface over the engine (reference:
    llm/_internal/serve/builders build_openai_app + the OpenAI-compatible
    router): POST /v1/completions and /v1/chat/completions bodies map
    onto engine requests; GET /v1/models lists the deployment. Streaming
    responses use SSE `data:` lines when "stream": true."""

    def __init__(self, llm_config: LLMConfig):
        super().__init__(llm_config)
        self.model_id = llm_config.model_id
        self.tokenizer = llm_config.tokenizer

    # -- token plumbing --
    def _encode(self, prompt):
        if isinstance(prompt, list):
            return [int(t) for t in prompt]
        if self.tokenizer is None:
            raise ValueError("string prompts need LLMConfig.tokenizer (encode/decode); token-id lists work without one")  # tpulint: disable=ERR002 — deployment-config validation (missing tokenizer): 400-class, fails every request identically
        return list(self.tokenizer.encode(prompt))

    def _decode(self, token_ids):
        if self.tokenizer is None:
            return token_ids
        return self.tokenizer.decode(token_ids)

    def _chat_to_prompt(self, messages):
        if self.tokenizer is not None and hasattr(self.tokenizer, "apply_chat_template"):
            return list(self.tokenizer.apply_chat_template(messages))
        text = "\n".join(f"{m.get('role', 'user')}: {m.get('content', '')}" for m in messages) + "\nassistant:"
        return self._encode(text)

    def _sampling(self, body: dict) -> dict:
        sp = {
            "max_tokens": int(body.get("max_tokens", 64)),
            "temperature": float(body.get("temperature", 0.0)),
            "top_p": float(body.get("top_p", 1.0)),
        }
        if body.get("seed") is not None:
            sp["seed"] = int(body["seed"])
        if body.get("stop_token_ids"):
            sp["stop_token_ids"] = tuple(body["stop_token_ids"])
        if body.get("priority") is not None:
            # request class for admission control (serve/overload.py):
            # 0 = shed first; higher classes shed only at the full caps
            sp["priority"] = int(body["priority"])
        return sp

    # -- HTTP entry --
    def __call__(self, request):
        path = getattr(request, "path", "/")
        if path.endswith("/models"):
            return {"object": "list", "data": [{"id": self.model_id, "object": "model", "owned_by": "ray_tpu"}]}
        body = request.json() if hasattr(request, "json") else dict(request)
        chat = path.endswith("/chat/completions")
        if chat:
            prompt_ids = self._chat_to_prompt(body.get("messages", []))
        else:
            prompt_ids = self._encode(body.get("prompt", []))
        if body.get("stream"):
            return self._stream_completion(prompt_ids, body, chat)
        out = self.generate(prompt_ids, self._sampling(body))
        text = self._decode(out["token_ids"])
        if chat:
            choice = {"index": 0, "message": {"role": "assistant", "content": text}, "finish_reason": out["finish_reason"]}
            obj = "chat.completion"
        else:
            choice = {"index": 0, "text": text, "finish_reason": out["finish_reason"]}
            obj = "text_completion"
        return {
            "id": out["request_id"],
            "object": obj,
            "model": self.model_id,
            "choices": [choice],
            "usage": {
                "prompt_tokens": len(out["prompt_token_ids"]),
                "completion_tokens": len(out["token_ids"]),
                "total_tokens": len(out["prompt_token_ids"]) + len(out["token_ids"]),
            },
        }

    def _stream_completion(self, prompt_ids, body: dict, chat: bool):
        """SSE chunks, one per generated token (reference: OpenAI
        streaming format). Serve streams these through the chunked proxy.

        NOT itself a generator: the admission check and the engine
        admission run EAGERLY here, so a shed streaming request raises
        its typed OverloadedError at call time — before any stream
        machinery engages — and the proxies (which fetch the first item
        before committing the 200 header) can surface the 429."""
        import queue as _queue

        from ray_tpu.llm import SamplingParams

        params = SamplingParams(**self._sampling(body))
        # streaming ingress guards exactly like the unary one
        self._check_alive()
        self._admission.check(params.priority)
        # we own the queue: a tiny request can finish (and leave the
        # engine registry) before add_request even returns, so the state
        # must never be looked up there afterwards
        out_q = _queue.SimpleQueue()
        rid = self.engine.add_request(list(prompt_ids), params, out_queue=out_q)
        self._work.set()
        if self._stopped:
            # raced a shutdown between the ingress check and admission
            # (the unary path's _await_finished guard, streaming flavor).
            # A request that COMPLETED in the race already has its tokens
            # and sentinel in out_q — serve them (mirroring the unary
            # path's pop-from-_done); otherwise nothing will ever step
            # it and the shutdown sweep may have already run, so fail
            # fast with the typed signal.
            with self.engine._lock:
                st = self.engine._requests.get(rid)
                unfinished = st is not None and not st.finished
            if unfinished:
                self.engine.abort_request(rid)
                raise ReplicaDrainingError(
                    "replica shut down while admitting", retry_after_s=1.0
                )
        return self._stream_tokens(rid, out_q, chat)

    def _stream_tokens(self, rid: str, out_q, chat: bool):
        """The generator half of _stream_completion (admission already
        done): drain the request's token queue into SSE chunks."""
        import json as _json
        import time as _time

        key = "delta" if chat else "text"
        obj = "chat.completion.chunk" if chat else "text_completion"
        deadline = _time.monotonic() + 300.0
        while True:
            if self._stepper_error is not None:
                raise StepperDiedError(f"llm stepper died:\n{self._stepper_error}")
            try:
                tok = out_q.get(timeout=min(5.0, max(0.1, deadline - _time.monotonic())))
            except _queue.Empty as e:
                if _time.monotonic() > deadline:
                    self.engine.abort_request(rid)
                    # typed (504, retryable) and chained: GetTimeoutError
                    # IS-A TimeoutError, so pre-taxonomy callers still match
                    raise GetTimeoutError(f"stream {rid} produced no token for 300s") from e
                continue
            if tok is None:
                if self._stepper_error is not None:
                    raise StepperDiedError(f"llm stepper died:\n{self._stepper_error}")
                break
            piece = self._decode([tok])
            content = {"role": "assistant", "content": piece} if chat else piece
            yield "data: " + _json.dumps(
                {"id": rid, "object": obj, "model": self.model_id, "choices": [{"index": 0, key: content}]}
            ) + "\n\n"
        yield "data: [DONE]\n\n"


class PrefillServer(LLMServer):
    """Prefill-only replica for disaggregated serving (llm/disagg/;
    reference: python/ray/llm/tests/serve/deployments/
    prefill_decode_disagg/ — the vLLM KV-connector split).

    Engine-backed: concurrent prefill calls enqueue prefill-only requests
    and the stepping thread BATCHES same-bucket prompts into one forward
    (the engine's admission + prefill stages; the decode stage never sees
    them). Each finished block is published as an OWNED object in this
    replica's process — the replica is the block's owner for its whole
    life — and only the tiny (meta, ref) pair travels back."""

    telemetry_stage = "prefill"

    def __init__(self, llm_config: LLMConfig):
        from dataclasses import replace as _replace

        kwargs = dict(llm_config.engine_kwargs)
        kwargs.setdefault("enable_prefix_caching", False)  # stateless by default
        super().__init__(_replace(llm_config, engine_kwargs=kwargs))

    def _prewarm_compile(self):
        # a prefill replica's hot path is prefill + extract, not decode
        self.engine.prefill_handoff([1, 2, 3])

    def _prewarm_probe(self):
        self.engine.prefill_handoff([1, 2, 3])

    def prefill(self, prompt_token_ids, timeout_s: float = 180.0) -> dict:
        """-> {"meta": {...}, "ref": ObjectRef}: the handoff publish half
        (llm/disagg/handoff.py)."""
        from ray_tpu.llm.disagg import publish_handoff
        from ray_tpu.llm.disagg.handoff import HandoffError

        self._check_alive()
        # class-blind capacity guard (the prefill ingress doesn't know the
        # request class; the class-aware shed ran at the decode ingress)
        self._admission.check_capacity()
        rid = self.engine.add_prefill_request(list(prompt_token_ids))
        try:
            out = self._await_finished(rid, timeout_s)
        except BaseException:
            # waiter gave up (timeout/stepper death) possibly AFTER the
            # prefill stage stashed the block: drop it or it leaks on the
            # replica forever
            self.engine.pop_handoff(rid)
            raise
        kv = self.engine.pop_handoff(rid)
        if out.finish_reason != "handoff" or kv is None:
            raise HandoffError(f"prefill-only request {rid} failed: {out.finish_reason}")
        meta, ref = publish_handoff(kv)
        return {"meta": meta, "ref": ref}

    def prefill_local(self, prompt_token_ids) -> dict:
        """Legacy by-value path (payload rides the reply instead of the
        owned-object plane); kept for callers without a direct plane."""
        return self.engine.prefill_remote(list(prompt_token_ids))


class DecodeServer(LLMServer):
    """Decode replica: admits handoff KV blocks (borrow -> fused
    scatter-in) and runs continuous batching decode-only from there —
    prompt compute and token generation scale independently. Speculative
    decoding composes: pass LLMConfig.speculative and the admitted lanes
    draft/verify exactly as local admissions do. Recompute-preemption
    re-prefills LOCALLY (vLLM semantics: the preempted sequence's
    prompt+generated re-admits on this replica, not through the router)."""

    telemetry_stage = "decode"

    def __init__(self, llm_config: LLMConfig, prefill_handle=None):
        super().__init__(llm_config)
        self.prefill_handle = prefill_handle

    def _prewarm_compile(self):
        super()._prewarm_compile()
        # warm the handoff admission path too: extract a local block and
        # scatter it back in, compiling the fused scatter-in and the
        # first-token sample for the smallest bucket before the replica
        # reports RUNNING (the EMA probe then re-measures warm)
        from ray_tpu.llm import SamplingParams

        kv = self.engine.prefill_handoff([1, 2, 3])
        self.engine.add_prefilled(kv, SamplingParams(max_tokens=2, temperature=0.0))
        while self.engine.has_unfinished():
            self.engine.step()

    def _admit(self, prompt_token_ids, params) -> str:
        """Legacy decode-as-ingress path (prefill_handle given): fetch the
        handoff ourselves, then admit."""
        from ray_tpu.llm.disagg import fetch_handoff

        if self.prefill_handle is None:
            return super()._admit(prompt_token_ids, params)
        out = self.prefill_handle.prefill.remote(list(prompt_token_ids)).result(timeout_s=180.0)
        kv = fetch_handoff(out["ref"], out["meta"])
        return self.engine.add_prefilled(kv, params)

    def generate_from_handoff(self, meta: dict, ref, sampling_params: dict | None = None, timeout_s: float = 300.0) -> dict:
        """Router path: borrow the published KV block (bounded-retry,
        zero-copy fetch), scatter it into this replica's cache/pool, and
        decode to completion. A lost handoff raises HandoffLostError to
        the router — the signal to re-prefill — instead of hanging."""
        from ray_tpu.llm import SamplingParams
        from ray_tpu.llm.disagg import fetch_handoff

        self._check_alive()
        params = SamplingParams(**(sampling_params or {}))
        # shed BEFORE borrowing the handoff: an overloaded decode replica
        # must bounce the router to a peer without touching the block
        self._admission.check(params.priority)
        kv = fetch_handoff(ref, meta)
        rid = self.engine.add_prefilled(kv, params)
        self._work.set()
        out = self._await_finished(rid, timeout_s)
        return {
            "request_id": out.request_id,
            "prompt_token_ids": out.prompt_token_ids,
            "token_ids": out.token_ids,
            "finish_reason": out.finish_reason,
        }


class DisaggRouterServer:
    """Ingress of the disaggregated graph: llm/disagg/router.py policy
    over the prefill and decode deployment handles. The router never
    touches KV bytes — it moves (meta, ref) pairs and owns the bounded
    retry budget for dead decode lanes and lost handoffs."""

    def __init__(self, llm_config: LLMConfig, prefill_handle, decode_handle, max_attempts: int = 3):
        from ray_tpu.llm.disagg import DisaggRouter

        self._prefill_handle = prefill_handle
        self._decode_handle = decode_handle

        def _prefill(prompt):
            out = prefill_handle.prefill.remote(prompt).result(timeout_s=180.0)
            return out["meta"], out["ref"]

        def _decode(meta, ref, prompt, sp):
            return decode_handle.generate_from_handoff.remote(meta, ref, sp).result(timeout_s=600.0)

        def _resume(meta, ref, sp):
            # resume-on-peer (llm/migrate.py): the pow-2 pick may land on
            # the draining replica again — it sheds typed and the
            # router's budgeted loop retries
            return decode_handle.resume_from_migration.remote(meta, ref, sp).result(timeout_s=600.0)

        self.router = DisaggRouter(
            _prefill, _decode, resume=_resume, max_attempts=max_attempts,
            telemetry_tags={"model": llm_config.model_id},
        )

    def generate(self, prompt_token_ids, sampling_params: dict | None = None) -> dict:
        return self.router.generate(list(prompt_token_ids), sampling_params)

    def disagg_stats(self) -> dict:
        return self.router.stats()

    def check_health(self):
        return True

    def __call__(self, request):
        body = request.json() if hasattr(request, "json") else dict(request)
        return self.generate(body["prompt_token_ids"], body.get("sampling_params"))


def build_pd_disagg_deployment(
    llm_config: LLMConfig,
    *,
    num_prefill_replicas: int = 1,
    num_decode_replicas: int = 1,
    name: str = "LLM",
    max_attempts: int = 3,
):
    """-> Application: router ingress over a prefill pool and a decode
    pool with the KV block shipped as an owned handoff object between
    them (llm/disagg/). N_prefill and N_decode scale independently; call
    .generate on the returned handle exactly like the monolithic
    deployment. Replicas pre-warm their compiles at creation
    (LLMConfig.prewarm) so fleet spin-up, not the first request, pays
    them."""
    from ray_tpu import serve

    health = {"health_check_timeout_s": 180.0, "health_check_period_s": 2.0}
    prefill_app = serve.deployment(
        name=f"{name}-prefill",
        num_replicas=num_prefill_replicas,
        max_ongoing_requests=llm_config.max_ongoing_requests,
        **health,
    )(PrefillServer).bind(llm_config)
    decode_app = serve.deployment(
        name=f"{name}-decode",
        num_replicas=num_decode_replicas,
        max_ongoing_requests=llm_config.max_ongoing_requests,
        **health,
    )(DecodeServer).bind(llm_config)
    router_dep = serve.deployment(
        name=f"{name}-router",
        num_replicas=1,
        max_ongoing_requests=llm_config.max_ongoing_requests * max(num_decode_replicas, 1),
        **health,
    )(DisaggRouterServer)
    return router_dep.bind(llm_config, prefill_app, decode_app, max_attempts)


class KVIndexServer:
    """Cluster prefix-index deployment (llm/kvplane/index.py): the ONE
    map every replica registers its published prefix blocks in and every
    router scores against. Control plane only — refs and small meta
    dicts, never KV bytes."""

    def __init__(self, ttl_s: float = 30.0):
        from ray_tpu.llm.kvplane import PrefixIndex

        self.index = PrefixIndex(ttl_s=ttl_s)

    def register(self, replica, entries):
        return self.index.register(replica, entries)

    def unregister(self, replica, keys):
        return self.index.unregister(replica, keys)

    def heartbeat(self, replica):
        return self.index.heartbeat(replica)

    def drop_replica(self, replica):
        return self.index.drop_replica(replica)

    def report_lost(self, replica, key):
        return self.index.report_lost(replica, key)

    def lookup(self, keys, exclude=None, requester=None):
        return self.index.lookup(keys, exclude, requester)

    def match_replicas(self, keys):
        return self.index.match_replicas(keys)

    def top_hot(self, k=4, exclude=None):
        return self.index.top_hot(k, exclude)

    def expire(self):
        return self.index.expire()

    def stats(self):
        return self.index.stats()

    def check_health(self):
        return True


class KVPlaneServer(LLMServer):
    """LLM replica joined to the cluster KV plane: its engine publishes
    freshly cached prefixes, serves remote hits over the object plane,
    and re-publishes what it fetches (llm/kvplane/client.py). Each
    replica registers under its deployment name so the router's
    cache-aware scores and the index's entries name the same thing."""

    def __init__(self, llm_config: LLMConfig, index_handle, replica_name: str,
                 publish_min_hits: int = 2, prefetch_k: int = 0):
        from dataclasses import replace as _replace

        from ray_tpu.llm.kvplane import KVPlaneClient
        from ray_tpu.llm.telemetry import default_tags

        self.replica_name = str(replica_name)
        kwargs = dict(llm_config.engine_kwargs)
        kwargs.setdefault(
            "telemetry_tags",
            default_tags(self.telemetry_stage, model=llm_config.model_id, replica=self.replica_name),
        )
        # publish_min_hits: the client's capacity-aware publication policy
        # (publish a prefix only once it shows reuse; 1 = publish-on-store).
        # prefetch_k > 0 turns on predictive prefetch: each heartbeat tick
        # pulls the fleet's top-k demanded prefix blocks into the local
        # cache ahead of demand (remote-tier hits become local-tier).
        kwargs.setdefault(
            "kv_plane",
            KVPlaneClient(index_handle, self.replica_name,
                          publish_min_hits=publish_min_hits, prefetch_k=prefetch_k),
        )
        super().__init__(_replace(llm_config, engine_kwargs=kwargs))

    def kvplane_stats(self) -> dict:
        """Tiered prefix-reuse counters (prefix_cache_stats with the
        local/remote split and the plane client's own accounting)."""
        return self.engine.prefix_cache_stats()


class KVRouterServer:
    """Cache-aware ingress over a pool of KVPlaneServer replicas
    (llm/kvplane/routing.py): scores every replica by longest cached
    prefix (index.match_replicas) blended with live load, so
    shared-prefix traffic lands where its KV already lives — local tier
    beats remote tier beats cold."""

    def __init__(
        self,
        llm_config: LLMConfig,
        index_handle,
        replica_names: tuple,
        *replica_handles,
        cache_weight: float = 1.0,
        load_weight: float = 0.1,
        max_attempts: int = 2,
    ):
        from ray_tpu.llm.kvplane import CacheAwareRouter

        names = [str(n) for n in replica_names]
        handles = dict(zip(names, replica_handles))
        block = int(llm_config.engine_kwargs.get("prefix_block", 64))

        def _submit(replica_id, prompt, sp):
            return handles[replica_id].generate.remote(prompt, sp).result(timeout_s=600.0)

        def _resume_submit(replica_id, meta, ref, sp):
            # resume-on-peer (llm/migrate.py): splice a preempted
            # replica's checkpoint on the next-ranked replica
            return handles[replica_id].resume_from_migration.remote(meta, ref, sp).result(timeout_s=600.0)

        self.router = CacheAwareRouter(
            index_handle, _submit, names, block=block,
            cache_weight=cache_weight, load_weight=load_weight, max_attempts=max_attempts,
            resume_submit=_resume_submit,
            telemetry_tags={"model": llm_config.model_id},
        )

    def generate(self, prompt_token_ids, sampling_params: dict | None = None) -> dict:
        return self.router.generate(list(prompt_token_ids), sampling_params)

    def kvplane_stats(self) -> dict:
        return self.router.stats()

    def check_health(self):
        return True

    def __call__(self, request):
        body = request.json() if hasattr(request, "json") else dict(request)
        return self.generate(body["prompt_token_ids"], body.get("sampling_params"))


def build_kvplane_deployment(
    llm_config: LLMConfig,
    *,
    num_replicas: int = 2,
    name: str = "LLM",
    index_ttl_s: float = 30.0,
    cache_weight: float = 1.0,
    load_weight: float = 0.1,
    max_attempts: int = 2,
    prefetch_k: int = 0,
):
    """-> Application: cache-aware router over ``num_replicas`` engine
    replicas sharing one cluster prefix index (llm/kvplane/). Replicas
    are SINGLE-replica deployments (``{name}-r<i>``) so the router can
    target the specific replica its score picked — the whole point of
    cache-aware routing; a pow-2 pick inside one deployment would throw
    the affinity away. ``prefetch_k`` > 0 arms predictive prefetch on
    every replica (each heartbeat pulls the fleet's top-k demanded
    prefixes into the local cache). Call ``.generate`` on the returned
    handle exactly like the monolithic deployment."""
    from ray_tpu import serve

    health = {"health_check_timeout_s": 180.0, "health_check_period_s": 2.0}
    index_app = serve.deployment(name=f"{name}-kvindex", num_replicas=1, **health)(
        KVIndexServer
    ).bind(index_ttl_s)
    names, apps = [], []
    for i in range(num_replicas):
        rn = f"{name}-r{i}"
        names.append(rn)
        apps.append(
            serve.deployment(
                name=rn, num_replicas=1,
                max_ongoing_requests=llm_config.max_ongoing_requests, **health,
            )(KVPlaneServer).bind(llm_config, index_app, rn, prefetch_k=prefetch_k)
        )
    router_dep = serve.deployment(
        name=f"{name}-router",
        num_replicas=1,
        max_ongoing_requests=llm_config.max_ongoing_requests * max(num_replicas, 1),
        **health,
    )(KVRouterServer)
    return router_dep.bind(
        llm_config, index_app, tuple(names), *apps,
        cache_weight=cache_weight, load_weight=load_weight, max_attempts=max_attempts,
    )


def _build_app(llm_config: LLMConfig, cls, name: str):
    """Shared deployment construction for both server surfaces."""
    from ray_tpu import serve

    opts = {
        "name": name,
        "max_ongoing_requests": llm_config.max_ongoing_requests,
        # engine construction + first prefill/decode compiles take tens of
        # seconds; don't let the controller shoot the replica meanwhile
        "health_check_timeout_s": 180.0,
        "health_check_period_s": 2.0,
    }
    if llm_config.autoscaling_config is not None:
        opts["autoscaling_config"] = llm_config.autoscaling_config
    else:
        opts["num_replicas"] = llm_config.num_replicas
    num_tpus = llm_config.num_tpus_per_replica
    if num_tpus < 0:
        # auto: a TP replica gang-reserves its chips (reference: vLLM
        # replicas request tensor_parallel_size accelerators via their PG)
        num_tpus = float(llm_config.tensor_parallel_size) if llm_config.tensor_parallel_size > 1 else 0.0
    if num_tpus:
        opts["num_tpus"] = num_tpus  # ReplicaConfig field
    deployment = serve.deployment(**opts)(cls)
    return deployment.bind(llm_config)


def build_openai_app(llm_config: LLMConfig, *, name: str = "OpenAIServer"):
    """-> a Serve Application exposing the OpenAI surface (reference:
    llm/_internal/serve/builders.py build_openai_app). Mount it at
    /v1 via serve.run(app, route_prefix="/v1") + serve.start(proxy=True)."""
    return _build_app(llm_config, OpenAIServer, name)


def build_llm_deployment(llm_config: LLMConfig, *, name: str = "LLMServer"):
    """-> a Serve Application running LLMServer replicas (reference:
    llm/_internal/serve/builders.py build_llm_deployment)."""
    return _build_app(llm_config, LLMServer, name)
