"""Model multiplexing: many models share a replica pool.

Reference parity: serve/multiplex.py (@serve.multiplexed LRU model cache
per replica + get_multiplexed_model_id()) and the router's model-aware
replica choice. A replica lazily loads models through the decorated
loader, keeps at most ``max_num_models_per_replica`` alive (LRU eviction
calls the evicted model's ``__del__``/``close`` if present), and requests
carry their model id via ``handle.options(multiplexed_model_id=...)`` —
the router keeps the id sticky to the replica that last served it, so a
hot model stays loaded on one replica instead of thrashing every cache.

    @serve.deployment
    class ModelServer:
        @serve.multiplexed(max_num_models_per_replica=3)
        async def get_model(self, model_id: str):
            return load_model(model_id)

        async def __call__(self, request):
            model = await self.get_model(serve.get_multiplexed_model_id())
            return model(request)
"""

from __future__ import annotations

import asyncio
import contextvars
import inspect
import threading
from collections import OrderedDict

_current_model_id: contextvars.ContextVar = contextvars.ContextVar("rt_multiplexed_model_id", default="")


def get_multiplexed_model_id() -> str:
    """The model id of the request being handled (reference:
    serve.get_multiplexed_model_id)."""
    return _current_model_id.get()


def _set_multiplexed_model_id(model_id: str):
    _current_model_id.set(model_id or "")


class _ModelCache:
    """Per-instance LRU of loaded models.

    Loads are SINGLE-FLIGHT per model id (concurrent first requests wait
    for one loader instead of double-loading and orphaning an instance).
    Eviction runs the victim's cleanup hook after a grace period: an
    in-flight request that fetched the model just before eviction keeps a
    live reference, and the delay lets it finish before cleanup frees
    backing resources (a full in-use refcount would need scoped usage the
    reference's API shape doesn't give callers either)."""

    def __init__(self, loader, max_models: int, evict_grace_s: float = 30.0):
        self._loader = loader
        self._max = max(1, int(max_models))
        self._grace = float(evict_grace_s)
        self._models: OrderedDict = OrderedDict()
        self._lock = threading.Lock()
        self._loading: dict[str, threading.Event] = {}

    @staticmethod
    def _run_hook(victim):
        for name in ("shutdown", "close", "__del__"):
            hook = getattr(victim, name, None)
            if callable(hook):
                try:
                    res = hook()
                    if inspect.iscoroutine(res):
                        # async cleanup gets its own loop (we may be on a
                        # pool thread with none running)
                        threading.Thread(target=asyncio.run, args=(res,), daemon=True).start()
                except Exception:
                    pass
                return

    def _evict_lru(self) -> list:
        """Pop LRU victims; caller runs _dispose(victims) OUTSIDE the
        lock (a slow user cleanup hook must not stall unrelated hits)."""
        victims = []
        while len(self._models) > self._max:
            _, victim = self._models.popitem(last=False)
            victims.append(victim)
        return victims

    def _dispose(self, victims: list):
        for victim in victims:
            if self._grace <= 0:
                self._run_hook(victim)
            else:
                t = threading.Timer(self._grace, self._run_hook, args=(victim,))
                t.daemon = True
                t.start()

    def loaded_ids(self) -> list:
        with self._lock:
            return list(self._models)

    def _begin(self, model_id: str):
        """-> ("hit", model) | ("load", event) | ("wait", event)."""
        with self._lock:
            if model_id in self._models:
                self._models.move_to_end(model_id)
                return ("hit", self._models[model_id])
            ev = self._loading.get(model_id)
            if ev is not None:
                return ("wait", ev)
            ev = self._loading[model_id] = threading.Event()
            return ("load", ev)

    def _commit(self, model_id: str, model, ev: threading.Event):
        with self._lock:
            self._models[model_id] = model
            self._models.move_to_end(model_id)
            victims = self._evict_lru()
            self._loading.pop(model_id, None)
        ev.set()
        self._dispose(victims)

    def _abort(self, model_id: str, ev: threading.Event):
        with self._lock:
            self._loading.pop(model_id, None)
        ev.set()

    def get_sync(self, obj, model_id: str):
        while True:
            state, x = self._begin(model_id)
            if state == "hit":
                return x
            if state == "wait":
                x.wait(timeout=300.0)
                continue  # loader finished (or failed): re-check
            try:
                model = self._loader(obj, model_id)
                if inspect.iscoroutine(model):
                    raise TypeError("async loader called from sync context; declare the caller async and await it")
            except BaseException:
                self._abort(model_id, x)
                raise
            self._commit(model_id, model, x)
            return model

    async def get_async(self, obj, model_id: str):
        while True:
            state, x = self._begin(model_id)
            if state == "hit":
                return x
            if state == "wait":
                await asyncio.get_running_loop().run_in_executor(None, x.wait, 300.0)
                continue
            try:
                if inspect.iscoroutinefunction(self._loader):
                    model = await self._loader(obj, model_id)
                else:
                    # a sync loader (multi-second weight load) must not
                    # block every concurrent request on the replica's
                    # event loop; the singleflight event already
                    # serializes duplicate loads
                    model = await asyncio.get_running_loop().run_in_executor(
                        None, self._loader, obj, model_id
                    )
                    if inspect.iscoroutine(model):
                        model = await model
            except BaseException:
                self._abort(model_id, x)
                raise
            self._commit(model_id, model, x)
            return model


class _MultiplexWrapper:
    """Descriptor form of @serve.multiplexed (method decoration)."""

    def __init__(self, loader, max_models: int, evict_grace_s: float = 30.0):
        self._loader = loader
        self._max = max_models
        self._grace = evict_grace_s
        self.__name__ = getattr(loader, "__name__", "get_model")
        self._is_async = inspect.iscoroutinefunction(loader)

    def __reduce__(self):
        # per-process cache state never travels; rebuild on the replica
        return (_MultiplexWrapper, (self._loader, self._max, self._grace))

    _cache_create_lock = threading.Lock()

    def _cache(self, obj) -> _ModelCache:
        key = f"__serve_mux_{self.__name__}"
        c = obj.__dict__.get(key)
        if c is None:
            with _MultiplexWrapper._cache_create_lock:
                c = obj.__dict__.setdefault(key, _ModelCache(self._loader, self._max, self._grace))
        return c

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        cache = self._cache(obj)
        if self._is_async:

            async def bound(model_id: str | None = None):
                return await cache.get_async(obj, model_id if model_id is not None else get_multiplexed_model_id())

        else:

            def bound(model_id: str | None = None):
                return cache.get_sync(obj, model_id if model_id is not None else get_multiplexed_model_id())

        bound.loaded_ids = cache.loaded_ids
        bound.__name__ = self.__name__
        return bound


def multiplexed(_fn=None, *, max_num_models_per_replica: int = 3, evict_grace_s: float = 30.0):
    """Decorator: see module docstring (reference: serve.multiplexed).
    ``evict_grace_s`` delays the evicted model's cleanup hook so requests
    that fetched it just before eviction can finish (0 = immediate)."""

    def wrap(fn):
        return _MultiplexWrapper(fn, max_num_models_per_replica, evict_grace_s)

    if _fn is not None:
        return wrap(_fn)
    return wrap
