"""Serve controller: owns target state and reconciles the world to it.

Reference parity: serve/_private/controller.py:102 (ServeController,
deploy_applications :760, run_control_loop), deployment_state.py (replica
state machine STARTING->RUNNING->STOPPING, health checks), and
autoscaling_state.py (request-metric autoscaling decisions).

One controller actor per cluster (named SERVE_CONTROLLER). A background
reconcile thread drives, per deployment:

  target replicas  ->  start/stop replica actors (STARTING -> RUNNING
  after first successful health ping; STOPPING drains then kills)
  health checks    ->  dead/unhealthy replicas are torn down and replaced
  autoscaling      ->  handle-reported (queued + ongoing) demand averaged
  over a look-back window; desired = demand / target_ongoing_requests,
  clamped to [min, max] with upscale/downscale delay smoothing

Routers (handles) long-poll `get_replicas(name, known_version)`: the
version bumps whenever the RUNNING set changes.
"""

from __future__ import annotations

import logging
import math
import threading
import time
import uuid
from collections import defaultdict, deque
from dataclasses import dataclass, field

import ray_tpu
from ray_tpu.serve._replica import Replica

logger = logging.getLogger("ray_tpu.serve")

CONTROLLER_NAME = "SERVE_CONTROLLER"


@dataclass
class ReplicaInfo:
    replica_id: str
    actor: object
    state: str = "STARTING"  # STARTING | RUNNING | STOPPING
    last_health_ok: float = field(default_factory=time.time)
    health_ref: object = None
    started_at: float = field(default_factory=time.time)
    stop_ref: object = None
    stop_deadline: float = 0.0


@dataclass
class DeploymentState:
    name: str
    app_name: str
    cls_or_fn: object
    init_args: tuple
    init_kwargs: dict
    config: object  # DeploymentConfig
    replica_config: object  # ReplicaConfig
    target_replicas: int = 1
    replicas: list = field(default_factory=list)
    version: int = 0
    # autoscaling bookkeeping
    handle_metrics: dict = field(default_factory=dict)  # handle_id -> (ts, ongoing+queued)
    demand_window: deque = field(default_factory=lambda: deque(maxlen=256))
    scale_decision_since: float | None = None
    scale_decision_dir: int = 0
    last_metrics_poll: float = 0.0

    def running(self):
        return [r for r in self.replicas if r.state == "RUNNING"]


class ServeController:
    def __init__(self, http_options=None):
        self._deployments: dict[str, DeploymentState] = {}  # key = app/name
        self._apps: dict[str, dict] = {}  # app -> {"deployments": [...], "ingress": str, "route_prefix": str}
        self._lock = threading.RLock()
        self._shutdown = False
        self._http_options = http_options
        self._proxy_actor = None
        self._thread = threading.Thread(target=self._control_loop, name="serve-controller", daemon=True)
        self._thread.start()

    # ------------------------------------------------------------ deploy API

    def deploy_application(self, app_name: str, deployments: list[dict], ingress: str, route_prefix: str = "/"):
        """deployments: [{name, cls_or_fn, init_args, init_kwargs, config,
        replica_config}] (pickled payloads arrive transparently via the
        task path)."""
        with self._lock:
            new_names = {f"{app_name}/{d['name']}" for d in deployments}
            # tear down deployments removed from the app
            for key in [k for k, ds in self._deployments.items() if ds.app_name == app_name and k not in new_names]:
                self._deployments[key].target_replicas = 0
                self._deployments[key].config.num_replicas = 0
            for d in deployments:
                key = f"{app_name}/{d['name']}"
                cur = self._deployments.get(key)
                cfg = d["config"]
                if cur is None:
                    ds = DeploymentState(
                        name=d["name"],
                        app_name=app_name,
                        cls_or_fn=d["cls_or_fn"],
                        init_args=d.get("init_args", ()),
                        init_kwargs=d.get("init_kwargs", {}),
                        config=cfg,
                        replica_config=d["replica_config"],
                        target_replicas=cfg.initial_target(),
                    )
                    self._deployments[key] = ds
                else:
                    # in-place update: new code/config; restart replicas by
                    # marking all for stop (reconcile will replace them)
                    cur.cls_or_fn = d["cls_or_fn"]
                    cur.init_args = d.get("init_args", ())
                    cur.init_kwargs = d.get("init_kwargs", {})
                    cur.config = cfg
                    cur.replica_config = d["replica_config"]
                    cur.target_replicas = cfg.initial_target()
                    for r in cur.replicas:
                        if r.state != "STOPPING":
                            r.state = "STOPPING"
            self._apps[app_name] = {
                "deployments": [d["name"] for d in deployments],
                "ingress": ingress,
                "route_prefix": route_prefix,
            }
        return True

    def delete_application(self, app_name: str):
        with self._lock:
            if app_name not in self._apps:
                return False
            for key, ds in self._deployments.items():
                if ds.app_name == app_name:
                    ds.target_replicas = 0
                    ds.config.num_replicas = 0
                    if ds.config.autoscaling_config:
                        ds.config.autoscaling_config = None
            del self._apps[app_name]
            return True

    def list_applications(self):
        with self._lock:
            return dict(self._apps)

    # -------------------------------------------------------------- routing

    def get_replicas(self, app_name: str, deployment: str, known_version: int = -1):
        """Returns (version, [(replica_id, actor_handle)], max_ongoing)."""
        key = f"{app_name}/{deployment}"
        with self._lock:
            ds = self._deployments.get(key)
            if ds is None:
                return (-1, [], 0)
            return (
                ds.version,
                [(r.replica_id, r.actor) for r in ds.running()],
                ds.config.max_ongoing_requests,
            )

    def get_ingress(self, app_name: str):
        with self._lock:
            app = self._apps.get(app_name)
            if app is None:
                return None
            return app["ingress"]

    def record_handle_metrics(self, app_name: str, deployment: str, handle_id: str, ongoing_plus_queued: int):
        """Handles push demand (in-flight + queued) here on their refresh
        tick; the autoscaler aggregates across handles (reference:
        autoscaling_state.py handle-metric path)."""
        key = f"{app_name}/{deployment}"
        with self._lock:
            ds = self._deployments.get(key)
            if ds is not None:
                ds.handle_metrics[handle_id] = (time.time(), int(ongoing_plus_queued))

    # --------------------------------------------------------------- status

    def get_deployment_status(self, app_name: str, deployment: str) -> dict:
        key = f"{app_name}/{deployment}"
        with self._lock:
            ds = self._deployments.get(key)
            if ds is None:
                return {"status": "NOT_FOUND"}
            running = len(ds.running())
            status = "HEALTHY" if running >= max(ds.target_replicas, 0) and ds.target_replicas >= 0 else "UPDATING"
            if ds.target_replicas > 0 and running == 0:
                status = "UPDATING"
            return {
                "status": status,
                "target_replicas": ds.target_replicas,
                "running_replicas": running,
                "version": ds.version,
            }

    def get_app_status(self, app_name: str) -> dict:
        with self._lock:
            app = self._apps.get(app_name)
            if app is None:
                return {"status": "NOT_FOUND", "deployments": {}}
            deps = {n: self.get_deployment_status(app_name, n) for n in app["deployments"]}
        ok = all(d["status"] == "HEALTHY" for d in deps.values())
        return {"status": "RUNNING" if ok else "DEPLOYING", "deployments": deps}

    def graceful_shutdown(self):
        with self._lock:
            self._shutdown = True
            for ds in self._deployments.values():
                for r in ds.replicas:
                    try:
                        ray_tpu.kill(r.actor)
                    except Exception:
                        pass
                ds.replicas.clear()
        return True

    # ------------------------------------------------------------ reconcile

    def _control_loop(self):
        while not self._shutdown:
            try:
                self._reconcile_once()
            except Exception:
                logger.exception("serve controller reconcile error")
            time.sleep(0.05)

    def _reconcile_once(self):
        with self._lock:
            states = list(self._deployments.items())
        for key, ds in states:
            with self._lock:
                if self._shutdown:
                    return
                self._autoscale(ds)
                self._scale_replicas(ds)
                self._check_health(ds)
            # drop fully-removed deployments
            with self._lock:
                if ds.target_replicas == 0 and not ds.replicas and ds.app_name not in self._apps:
                    self._deployments.pop(key, None)

    def _start_replica(self, ds: DeploymentState):
        rid = f"{ds.name}#{uuid.uuid4().hex[:6]}"
        opts = ds.replica_config.to_actor_options()
        # +3 slots: health checks / metrics / reconfigure must not starve
        # behind user requests filling max_ongoing_requests
        opts["max_concurrency"] = ds.config.max_ongoing_requests + 3
        actor = ray_tpu.remote(Replica).options(**opts).remote(
            ds.name, rid, ds.cls_or_fn, ds.init_args, ds.init_kwargs, ds.config.user_config
        )
        info = ReplicaInfo(replica_id=rid, actor=actor)
        info.health_ref = actor.check_health.remote()
        ds.replicas.append(info)

    def _finalize_stopping(self, ds: DeploymentState):
        """Graceful drain: prepare_shutdown first, kill when it completes
        (or the graceful timeout passes)."""
        now = time.time()
        for info in [r for r in ds.replicas if r.state == "STOPPING"]:
            if info.stop_ref is None:
                try:
                    info.stop_ref = info.actor.prepare_shutdown.remote(ds.config.graceful_shutdown_timeout_s)
                except Exception:
                    info.stop_ref = None
                info.stop_deadline = now + ds.config.graceful_shutdown_timeout_s + 1.0
                ds.version += 1  # routers drop it immediately
                continue
            done, _ = ray_tpu.wait([info.stop_ref], timeout=0)
            if done or now >= info.stop_deadline:
                try:
                    ray_tpu.kill(info.actor, no_restart=True)
                except Exception:
                    pass
                ds.replicas.remove(info)

    def _scale_replicas(self, ds: DeploymentState):
        self._finalize_stopping(ds)
        alive = [r for r in ds.replicas if r.state in ("STARTING", "RUNNING")]
        if len(alive) < ds.target_replicas:
            for _ in range(ds.target_replicas - len(alive)):
                self._start_replica(ds)
        elif len(alive) > ds.target_replicas:
            # prefer stopping STARTING replicas, then youngest RUNNING
            excess = len(alive) - ds.target_replicas
            victims = sorted(alive, key=lambda r: (r.state == "RUNNING", r.started_at))
            for info in victims[:excess]:
                info.state = "STOPPING"

    def _check_health(self, ds: DeploymentState):
        now = time.time()
        for info in list(ds.replicas):
            if info.state == "STOPPING":
                continue
            if info.health_ref is not None:
                ready, _ = ray_tpu.wait([info.health_ref], timeout=0)
                if ready:
                    try:
                        ray_tpu.get(info.health_ref)
                        info.last_health_ok = now
                        if info.state == "STARTING":
                            info.state = "RUNNING"
                            ds.version += 1
                    except Exception:
                        logger.warning("replica %s failed health check; replacing", info.replica_id)
                        info.state = "STOPPING"
                    info.health_ref = None
            elif now - info.last_health_ok > ds.config.health_check_period_s:
                info.health_ref = info.actor.check_health.remote()
            if now - info.last_health_ok > ds.config.health_check_timeout_s:
                logger.warning("replica %s health check timed out; replacing", info.replica_id)
                info.state = "STOPPING"

    # ------------------------------------------------------------ autoscale

    def _autoscale(self, ds: DeploymentState):
        cfg = ds.config.autoscaling_config
        if cfg is None:
            ds.target_replicas = 0 if ds.config.num_replicas == 0 else (ds.config.num_replicas or 1)
            return
        now = time.time()
        if now - ds.last_metrics_poll < cfg.metrics_interval_s:
            return
        ds.last_metrics_poll = now
        # total demand = handle-reported in-flight + queued (stale handles expire)
        fresh = {h: v for h, (ts, v) in ds.handle_metrics.items() if now - ts < 4 * cfg.metrics_interval_s + 1.0}
        demand = sum(fresh.values())
        ds.handle_metrics = {h: (ts, v) for h, (ts, v) in ds.handle_metrics.items() if h in fresh}
        ds.demand_window.append((now, demand))
        window = [v for (ts, v) in ds.demand_window if now - ts <= cfg.look_back_period_s]
        avg_demand = sum(window) / max(len(window), 1)

        cur = ds.target_replicas
        desired = math.ceil(avg_demand / max(cfg.target_ongoing_requests, 1e-6) - 1e-9)
        if desired > cur:
            desired = cur + max(1, math.ceil((desired - cur) * cfg.upscaling_factor))
        elif desired < cur:
            desired = cur - max(1, math.ceil((cur - desired) * cfg.downscaling_factor))
        desired = max(cfg.min_replicas, min(cfg.max_replicas, desired))

        direction = (desired > cur) - (desired < cur)
        if direction == 0:
            ds.scale_decision_since = None
            ds.scale_decision_dir = 0
            return
        if ds.scale_decision_dir != direction:
            ds.scale_decision_dir = direction
            ds.scale_decision_since = now
        delay = cfg.upscale_delay_s if direction > 0 else cfg.downscale_delay_s
        if now - (ds.scale_decision_since or now) >= delay:
            ds.target_replicas = desired
            ds.scale_decision_since = None
            ds.scale_decision_dir = 0
