"""Replica actor: hosts one instance of a deployment's user class.

Reference parity: serve/_private/replica.py (UserCallableWrapper with a
dedicated user-code event loop, handle_request / handle_request_streaming,
health checks, graceful shutdown) — collapsed to a single actor class.
Sync callables run on the actor's max_concurrency thread pool; coroutines
and async generators run on ONE persistent replica event loop (the
reference's user-callable loop), so async deployments don't pay a loop per
request. Streaming methods yield through the runtime's streaming-generator
machinery back to the caller.
"""

from __future__ import annotations

import asyncio
import inspect
import threading
import time


def _resolve_handle_markers(v):
    """Bound sub-deployments arrive as _HandleMarker; turn them into live
    DeploymentHandles inside the replica process (model composition)."""
    from ray_tpu.serve.deployment import _HandleMarker

    if isinstance(v, _HandleMarker):
        import ray_tpu
        from ray_tpu.serve._controller import CONTROLLER_NAME
        from ray_tpu.serve.handle import DeploymentHandle

        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        return DeploymentHandle(controller, v.app_name, v.deployment)
    return v


class Replica:
    """Wraps the user callable. Instantiated as a ray_tpu actor by the
    controller with max_concurrency = max_ongoing_requests + headroom for
    control calls (health/metrics)."""

    def __init__(self, deployment_name: str, replica_id: str, cls_or_fn, init_args, init_kwargs, user_config=None):
        self.deployment_name = deployment_name
        self.replica_id = replica_id
        self._lock = threading.Lock()
        self._ongoing = 0
        self._total = 0
        self._created_at = time.time()
        # one persistent loop for all async user code (reference: the
        # replica's user-code event loop, serve/_private/replica.py)
        self._loop = asyncio.new_event_loop()
        self._loop_thread = threading.Thread(target=self._loop.run_forever, name="serve-user-loop", daemon=True)
        self._loop_thread.start()
        init_args = tuple(_resolve_handle_markers(a) for a in (init_args or ()))
        init_kwargs = {k: _resolve_handle_markers(v) for k, v in (init_kwargs or {}).items()}
        if inspect.isfunction(cls_or_fn):
            self._callable = cls_or_fn
            self._is_function = True
        else:
            self._callable = cls_or_fn(*init_args, **init_kwargs)
            self._is_function = False
        if user_config is not None:
            self.reconfigure(user_config)

    # -- control plane --

    def check_health(self) -> bool:
        user_check = getattr(self._callable, "check_health", None)
        if user_check is not None and not self._is_function:
            user_check()
        return True

    def get_metrics(self) -> dict:
        with self._lock:
            return {
                "replica_id": self.replica_id,
                "ongoing_requests": self._ongoing,
                "total_requests": self._total,
                "uptime_s": time.time() - self._created_at,
            }

    def reconfigure(self, user_config):
        fn = getattr(self._callable, "reconfigure", None)
        if fn is not None:
            fn(user_config)

    def _drain_hook(self):
        """The deployment's drain lifecycle hook, iff it matches the
        contract (accepts timeout_s — serve/llm.py LLMServer.drain): a
        user method merely NAMED drain with a different signature is not
        the hook and must not be mis-called."""
        if self._is_function:
            return None
        hook = getattr(self._callable, "drain", None)
        if not callable(hook):
            return None
        try:
            inspect.signature(hook).bind(timeout_s=0.0)
        except TypeError:
            return None
        return hook

    def prepare_shutdown(self, timeout_s: float = 5.0):
        """Drain in-flight requests, then run the deployment's cleanup
        hook — `drain(timeout_s=...)`/`shutdown()`/`close()`/`__del__`
        in that order (reference: replica graceful shutdown calls the
        user __del__). A contract-matching drain hook gets the WHOLE
        budget and owns the bounded finish-in-flight wait itself;
        otherwise this method waits for in-flight requests first."""
        deadline = time.time() + timeout_s
        drain = self._drain_hook()
        if drain is None:
            while time.time() < deadline:
                with self._lock:
                    if self._ongoing == 0:
                        break
                time.sleep(0.02)
        if not self._is_function:
            for name in ("drain", "shutdown", "close", "__del__"):
                if name == "drain":
                    hook, kwargs = drain, {"timeout_s": max(deadline - time.time(), 0.0)}
                else:
                    hook, kwargs = getattr(self._callable, name, None), {}
                if not callable(hook):
                    continue
                try:
                    res = hook(**kwargs)
                    if inspect.iscoroutine(res):
                        asyncio.run_coroutine_threadsafe(res, self._loop).result(timeout=timeout_s)
                except Exception:
                    pass
                break
        with self._lock:
            drained = self._ongoing == 0
        if drained:
            # only a drained loop may stop: an in-flight coroutine on a
            # stopped loop would hang its handler thread forever
            try:
                self._loop.call_soon_threadsafe(self._loop.stop)
            except Exception:
                pass
        return True

    # -- data plane --

    def _target(self, method_name: str):
        if self._is_function:
            return self._callable
        return getattr(self._callable, method_name)

    @staticmethod
    def _set_model_id(model_id):
        from ray_tpu.serve.multiplex import _set_multiplexed_model_id

        _set_multiplexed_model_id(model_id or "")

    def _with_model_ctx(self, coro, model_id):
        """Carry the request's model id onto the actor event loop (the
        contextvar set in this pool thread doesn't cross threads)."""

        async def _inner():
            self._set_model_id(model_id)
            return await coro

        return _inner()

    def handle_request(self, method_name: str, args: tuple, kwargs: dict, multiplexed_model_id: str | None = None):
        with self._lock:
            self._ongoing += 1
            self._total += 1
        try:
            self._set_model_id(multiplexed_model_id)
            result = self._target(method_name)(*args, **(kwargs or {}))
            if inspect.iscoroutine(result):
                result = asyncio.run_coroutine_threadsafe(
                    self._with_model_ctx(result, multiplexed_model_id), self._loop
                ).result()
            return result
        finally:
            with self._lock:
                self._ongoing -= 1

    def handle_request_streaming(self, method_name: str, args: tuple, kwargs: dict, multiplexed_model_id: str | None = None):
        """Generator method: items stream back through the runtime's
        streaming-generator path (reference: handle_request_streaming,
        serve/_private/replica.py). Called with num_returns='streaming'."""
        with self._lock:
            self._ongoing += 1
            self._total += 1
        try:
            self._set_model_id(multiplexed_model_id)
            result = self._target(method_name)(*args, **(kwargs or {}))
            if inspect.iscoroutine(result):
                result = asyncio.run_coroutine_threadsafe(
                    self._with_model_ctx(result, multiplexed_model_id), self._loop
                ).result()
            if inspect.isasyncgen(result):
                while True:
                    try:
                        item = asyncio.run_coroutine_threadsafe(result.__anext__(), self._loop).result()
                    except StopAsyncIteration:
                        return
                    yield item
            elif inspect.isgenerator(result):
                yield from result
            else:
                yield result  # unary fallback: stream of one
        finally:
            with self._lock:
                self._ongoing -= 1
