"""Replica actor: hosts one instance of a deployment's user class.

Reference parity: serve/_private/replica.py (UserCallableWrapper,
handle_request, health checks, graceful shutdown) — collapsed to a single
actor class. Concurrency comes from the actor's max_concurrency thread
pool; the replica tracks its in-flight count, which is both the router's
load signal (pow-2 choice) and the autoscaler's metric.
"""

from __future__ import annotations

import inspect
import threading
import time


def _resolve_handle_markers(v):
    """Bound sub-deployments arrive as _HandleMarker; turn them into live
    DeploymentHandles inside the replica process (model composition)."""
    from ray_tpu.serve.deployment import _HandleMarker

    if isinstance(v, _HandleMarker):
        import ray_tpu
        from ray_tpu.serve._controller import CONTROLLER_NAME
        from ray_tpu.serve.handle import DeploymentHandle

        controller = ray_tpu.get_actor(CONTROLLER_NAME)
        return DeploymentHandle(controller, v.app_name, v.deployment)
    return v


class Replica:
    """Wraps the user callable. Instantiated as a ray_tpu actor by the
    controller with max_concurrency = max_ongoing_requests + headroom for
    control calls (health/metrics)."""

    def __init__(self, deployment_name: str, replica_id: str, cls_or_fn, init_args, init_kwargs, user_config=None):
        self.deployment_name = deployment_name
        self.replica_id = replica_id
        self._lock = threading.Lock()
        self._ongoing = 0
        self._total = 0
        self._created_at = time.time()
        init_args = tuple(_resolve_handle_markers(a) for a in (init_args or ()))
        init_kwargs = {k: _resolve_handle_markers(v) for k, v in (init_kwargs or {}).items()}
        if inspect.isfunction(cls_or_fn):
            self._callable = cls_or_fn
            self._is_function = True
        else:
            self._callable = cls_or_fn(*init_args, **init_kwargs)
            self._is_function = False
        if user_config is not None:
            self.reconfigure(user_config)

    # -- control plane --

    def check_health(self) -> bool:
        user_check = getattr(self._callable, "check_health", None)
        if user_check is not None and not self._is_function:
            user_check()
        return True

    def get_metrics(self) -> dict:
        with self._lock:
            return {
                "replica_id": self.replica_id,
                "ongoing_requests": self._ongoing,
                "total_requests": self._total,
                "uptime_s": time.time() - self._created_at,
            }

    def reconfigure(self, user_config):
        fn = getattr(self._callable, "reconfigure", None)
        if fn is not None:
            fn(user_config)

    def prepare_shutdown(self, timeout_s: float = 5.0):
        """Drain: wait until in-flight requests finish (or timeout)."""
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            with self._lock:
                if self._ongoing == 0:
                    break
            time.sleep(0.02)
        shutdown = getattr(self._callable, "__del__", None)
        return True

    # -- data plane --

    def handle_request(self, method_name: str, args: tuple, kwargs: dict):
        with self._lock:
            self._ongoing += 1
            self._total += 1
        try:
            if self._is_function:
                fn = self._callable
            else:
                fn = getattr(self._callable, method_name)
            result = fn(*args, **(kwargs or {}))
            if inspect.iscoroutine(result):
                import asyncio

                result = asyncio.new_event_loop().run_until_complete(result)
            return result
        finally:
            with self._lock:
                self._ongoing -= 1
