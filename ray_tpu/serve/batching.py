"""Request batching: fuse concurrent single-item calls into one batch call.

Reference parity: serve/batching.py:535 (@serve.batch) — a decorated
method takes a LIST of requests and returns a list of results of the same
length; callers pass single items and get single results. Concurrent
callers (replica thread pool or coroutines) are fused: the batcher waits
up to ``batch_wait_timeout_s`` for up to ``max_batch_size`` items, then
invokes the wrapped function once.

    @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.01)
    def score(self, inputs: list) -> list: ...

    def __call__(self, req):        # N concurrent callers -> 1 score() call
        return self.score(req)

Sync callers block on their item's future; async callers (coroutine
context) can ``await wrapper.remote_async(item)``. Async wrapped functions
run on the batcher's private event loop.
"""

from __future__ import annotations

import asyncio
import inspect
import queue
import threading


class _Batcher:
    def __init__(self, fn, max_batch_size: int, batch_wait_timeout_s: float):
        self._fn = fn
        self._max = max(1, int(max_batch_size))
        self._wait_s = float(batch_wait_timeout_s)
        self._q: queue.Queue = queue.Queue()
        self._started = threading.Lock()
        self._thread = None
        self._loop = None  # lazily created for async wrapped fns

    def submit(self, bound_args: tuple):
        import concurrent.futures

        fut = concurrent.futures.Future()
        # enqueue BEFORE ensuring the thread: the idle-exit path re-checks
        # queue emptiness under the same lock, so the item can't strand
        self._q.put((bound_args, fut))
        self._ensure_thread()
        return fut

    def _ensure_thread(self):
        with self._started:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(target=self._run, name="serve-batcher", daemon=True)
                self._thread.start()

    def _call_fn(self, self_obj, items: list):
        args = (self_obj, items) if self_obj is not _NO_SELF else (items,)
        result = self._fn(*args)
        if inspect.iscoroutine(result):
            if self._loop is None:
                self._loop = asyncio.new_event_loop()
                threading.Thread(target=self._loop.run_forever, name="serve-batcher-loop", daemon=True).start()
            result = asyncio.run_coroutine_threadsafe(result, self._loop).result()
        return result

    def _run(self):
        import time

        while True:
            try:
                bound_args, fut = self._q.get(timeout=30.0)
            except queue.Empty:
                # idle exit so short-lived instances don't each pin a
                # thread forever; submit() restarts on demand
                with self._started:
                    if self._q.empty():
                        self._thread = None
                        if self._loop is not None:
                            try:
                                self._loop.call_soon_threadsafe(self._loop.stop)
                            except Exception:
                                pass
                            self._loop = None
                        return
                continue
            batch = [(bound_args, fut)]
            deadline = time.monotonic() + self._wait_s
            while len(batch) < self._max:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    batch.append(self._q.get(timeout=remaining))
                except queue.Empty:
                    break
            self_obj = batch[0][0][0]
            items = [a[1] for a, _ in batch]
            try:
                results = self._call_fn(self_obj, items)
                if not isinstance(results, list) or len(results) != len(items):
                    raise TypeError(
                        f"@serve.batch function must return a list of length {len(items)}, got {type(results).__name__}"
                    )
            except BaseException as e:  # noqa: BLE001
                for _, f in batch:
                    if not f.done():
                        f.set_exception(e)
                continue
            for (_, f), r in zip(batch, results):
                if not f.done():
                    f.set_result(r)


_NO_SELF = object()


class _BatchWrapper:
    """Descriptor so the decorator works on both methods and functions."""

    def __init__(self, fn, max_batch_size: int, batch_wait_timeout_s: float):
        self._fn = fn
        self._max_batch_size = max_batch_size
        self._batch_wait_timeout_s = batch_wait_timeout_s
        self._batcher = _Batcher(fn, max_batch_size, batch_wait_timeout_s)
        self.__name__ = getattr(fn, "__name__", "batched")
        self.__doc__ = getattr(fn, "__doc__", None)

    def __reduce__(self):
        # the batcher (locks, queue, thread) is per-process state: ship
        # only the wrapped fn + knobs, rebuild on the replica
        return (_BatchWrapper, (self._fn, self._max_batch_size, self._batch_wait_timeout_s))

    def _instance_batcher(self, obj) -> _Batcher:
        """One batcher per INSTANCE: items from different instances must
        never fuse (they would all run against batch[0]'s self)."""
        key = f"__serve_batcher_{self.__name__}"
        b = obj.__dict__.get(key)
        if b is None:
            b = obj.__dict__[key] = _Batcher(self._fn, self._max_batch_size, self._batch_wait_timeout_s)
        return b

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        batcher = self._instance_batcher(obj)

        def bound(item):
            return batcher.submit((obj, item)).result()

        async def bound_async(item):
            return await asyncio.wrap_future(batcher.submit((obj, item)))

        bound.remote_async = bound_async
        bound.__name__ = self.__name__
        return bound

    def __call__(self, item):
        return self._batcher.submit((_NO_SELF, item)).result()

    async def remote_async(self, item):
        return await asyncio.wrap_future(self._batcher.submit((_NO_SELF, item)))


def batch(_fn=None, *, max_batch_size: int = 10, batch_wait_timeout_s: float = 0.01):
    """Decorator: see module docstring. Usable bare (@serve.batch) or with
    arguments (@serve.batch(max_batch_size=...))."""

    def wrap(fn):
        return _BatchWrapper(fn, max_batch_size, batch_wait_timeout_s)

    if _fn is not None:
        return wrap(_fn)
    return wrap
