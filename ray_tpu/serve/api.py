"""serve public API: run / delete / status / handles / shutdown.

Reference parity: serve/api.py (serve.run :578, serve.delete, serve.status)
and _private/api.py (serve_start / client plumbing, collapsed: the
controller is one named actor, created on first use).
"""

from __future__ import annotations

import time

import ray_tpu
from ray_tpu.serve._controller import CONTROLLER_NAME, ServeController
from ray_tpu.serve.config import HTTPOptions
from ray_tpu.serve.deployment import Application, build_app_spec
from ray_tpu.serve.handle import DeploymentHandle

_http_proxy = None
_grpc_proxy = None


def _get_or_create_controller(http_options: HTTPOptions | None = None):
    ray_tpu.api._auto_init()
    try:
        return ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        pass
    return (
        ray_tpu.remote(ServeController)
        .options(name=CONTROLLER_NAME, max_concurrency=32, max_restarts=1)
        .remote(http_options)
    )


def start(http_options: HTTPOptions | None = None, proxy: bool = False, grpc_port: int | None = None):
    """Start the Serve control plane (idempotent); optionally the HTTP
    proxy and/or the gRPC ingress (reference: serve.start(http_options=
    ..., grpc_options=...); grpc_port=0 picks a free port — read it back
    from serve.api._grpc_proxy.port)."""
    controller = _get_or_create_controller(http_options)
    if proxy:
        _ensure_proxy(controller, http_options or HTTPOptions())
    if grpc_port is not None:
        global _grpc_proxy
        if _grpc_proxy is None:
            from ray_tpu.serve._grpc_proxy import GrpcProxy

            _grpc_proxy = GrpcProxy(controller, port=grpc_port)
    return controller


def _ensure_proxy(controller, http_options: HTTPOptions):
    global _http_proxy
    if _http_proxy is None:
        if getattr(http_options, "async_proxy", True):
            from ray_tpu.serve._async_proxy import AsyncHTTPProxy as _Proxy
        else:
            from ray_tpu.serve._proxy import HTTPProxy as _Proxy

        _http_proxy = _Proxy(controller, http_options)
        _http_proxy.start()
    return _http_proxy


def run(app: Application, name: str = "default", route_prefix: str = "/", *, blocking_timeout_s: float = 60.0, _blocking: bool = True):
    """Deploy an application and wait for it to be RUNNING; returns the
    ingress DeploymentHandle (reference serve/api.py:578)."""
    controller = _get_or_create_controller()
    specs, ingress = build_app_spec(app, name)
    ray_tpu.get(controller.deploy_application.remote(name, specs, ingress, route_prefix))
    if _blocking:
        deadline = time.time() + blocking_timeout_s
        while time.time() < deadline:
            st = ray_tpu.get(controller.get_app_status.remote(name))
            if st["status"] == "RUNNING":
                break
            time.sleep(0.1)
        else:
            raise TimeoutError(f"application {name!r} did not become RUNNING within {blocking_timeout_s}s: {st}")
    return DeploymentHandle(controller, name, ingress)


def delete(name: str):
    controller = _get_or_create_controller()
    ray_tpu.get(controller.delete_application.remote(name))


def status() -> dict:
    controller = _get_or_create_controller()
    apps = ray_tpu.get(controller.list_applications.remote())
    return {"applications": {a: ray_tpu.get(controller.get_app_status.remote(a)) for a in apps}}


def get_app_handle(name: str = "default") -> DeploymentHandle:
    controller = _get_or_create_controller()
    ingress = ray_tpu.get(controller.get_ingress.remote(name))
    if ingress is None:
        raise ValueError(f"no application named {name!r}")
    return DeploymentHandle(controller, name, ingress)


def get_deployment_handle(deployment: str, app_name: str = "default") -> DeploymentHandle:
    controller = _get_or_create_controller()
    return DeploymentHandle(controller, app_name, deployment)


def shutdown():
    """Tear down all applications, replicas, proxies, and the controller."""
    global _grpc_proxy, _http_proxy
    if _http_proxy is not None:
        _http_proxy.stop()
        _http_proxy = None
    if _grpc_proxy is not None:
        _grpc_proxy.stop()
        _grpc_proxy = None
    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        return
    try:
        ray_tpu.get(controller.graceful_shutdown.remote(), timeout=10)
    except Exception:
        pass
    try:
        ray_tpu.kill(controller)
    except Exception:
        pass
