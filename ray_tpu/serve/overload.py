"""Overload-robust serving: admission control, load shedding, drain.

The serving fleet's degradation order under pressure is FIXED:

    shed (lowest request class first)  ->  queue-wait  ->  never decode ITL

PR 9 built the sensors (live TTFT/ITL/queue-depth/KV-occupancy host
shadow state); this module is the actuator. ``AdmissionController``
bounds admission at every serving ingress: past the caps a request is
REJECTED with a typed ``OverloadedError`` (HTTP 429 + retry-after)
instead of joining a queue that can only grow — so overload shows up as
shed rate and queue wait in the telemetry plane while in-flight decode
lanes keep their ITL (the monolithic engine's failure mode is admission
waves whose prefill forwards stall every live decode stream; see
bench_serve.py ``engine_overload_ab``).

Everything the controller reads is HOST state: ``engine.host_load()``
(scheduler shadow queue/slot/occupancy counters — zero device sync, the
PR 9 rule) and the telemetry plane's live ITL / service-time EMAs for
the estimated-queue-wait test. The admission check runs per REQUEST at
the serve ingress, never inside ``engine.step`` — the 1.05x
zero-overhead gate is untouched by construction.

Request classes: ``SamplingParams.priority`` (ingress body key
``priority``), 0 = lowest. Each cap is scaled by the class's fraction
(``AdmissionConfig.class_fracs``), so the lowest class sheds first and
the highest class only sheds at the full cap — strict shed-lowest-first
without any cross-request reordering.

``RetryBudget`` is the ONE per-request failover budget the disagg and
kvplane routers both consume (previously each had its own ad-hoc bounded
retry); exhaustion is counted into ``rt_llm_retry_budget_exhausted_total``.

Replica drain rides the same plane: a draining replica sheds every new
request with ``ReplicaDrainingError`` (a 429 subclass — routers fail
over exactly like overload), finishes in-flight work, unregisters its
cluster-plane prefixes and releases owned handoff blocks before the
stepper exits (``serve/llm.py LLMServer.drain``).
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass

from ray_tpu.exceptions import SERVING_ERRORS, serving_error

# Jitter for 429 retry hints: every shed client sleeping EXACTLY
# retry_after_s re-arrives as one synchronized herd and re-saturates the
# replica it just backed off from; ±25% spread de-phases them. A
# dedicated seeded RNG (never the global one) keeps shed behavior
# independent of test/chaos seeding while staying deterministic per
# process. Bounds (0.75x..1.25x the clamped estimate) are locked by
# tests/test_llm_chaos.py.
RETRY_JITTER_FRAC = 0.25
_retry_jitter = random.Random(0x52455452)  # "RETR"


@serving_error
class OverloadedError(RuntimeError):
    """Typed admission rejection: the replica (or the whole fleet, when a
    router exhausts its failover budget on overloaded replicas) cannot
    take this request NOW. Maps to HTTP 429; ``retry_after_s`` is the
    ingress's backoff hint (the estimated queue wait, clamped)."""

    def __init__(self, msg: str, *, retry_after_s: float = 1.0, shed_class: int = 0):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)
        self.shed_class = int(shed_class)


@serving_error
class ReplicaDrainingError(OverloadedError):
    """The replica is draining (finish-in-flight only): routers treat it
    exactly like overload — fail over to another replica, never wait."""


@serving_error
class StepperDiedError(RuntimeError):
    """The replica's stepper thread died mid-flight: every waiter on this
    replica fails with the stepper's traceback as context, and another
    replica can serve the retry (503 + retryable in ``SERVING_ERRORS``).
    Subclasses RuntimeError so pre-taxonomy callers that matched the old
    bare ``RuntimeError("llm stepper died")`` keep working."""


def _causes(e: BaseException | None):
    """Bounded walk of an error's wire-wrapping chain (TaskError's
    ``.cause`` links) — the ONE traversal every typed-error probe below
    shares, so 429 detection, retry hints and class labels can never
    diverge on how deep or which links they follow."""
    for _ in range(8):
        if e is None:
            return
        yield e
        e = getattr(e, "cause", None)


def is_overloaded(e: BaseException | None) -> bool:
    """True when ``e`` is (or wraps) an OverloadedError. Under Serve the
    replica's exception crosses the wire inside TaskError: follow the
    ``.cause`` chain and fall back to the remote traceback string for
    causes that didn't survive pickling (same pattern as the disagg
    router's HandoffLostError detection)."""
    for err in _causes(e):
        if isinstance(err, OverloadedError):
            return True
        tb = getattr(err, "tb_str", "")
        if "OverloadedError" in tb or "ReplicaDrainingError" in tb:
            return True
    return False


def retry_hint_of(e: BaseException | None, default: float = 1.0) -> float:
    """The replica's backoff hint, dug out of a possibly wire-wrapped
    error: the FIRST ``retry_after_s`` along the cause chain (a
    TaskError wrapper has none — the shed replica's real hint sits on
    the wrapped OverloadedError)."""
    for err in _causes(e):
        retry = getattr(err, "retry_after_s", None)
        if retry is not None:
            return float(retry)
    return default


def shed_class_of(e: BaseException | None, default: int = 0) -> int:
    """The CLAMPED request class the shedding replica actually used,
    dug off the cause chain (OverloadedError.shed_class): routers reuse
    it so the shed metric's class label agrees between the replica and
    router stages."""
    for err in _causes(e):
        cls = getattr(err, "shed_class", None)
        if cls is not None:
            return int(cls)
    return default


def http_error_of(e: BaseException | None):
    """(status_code, body) for typed serving errors crossing the HTTP
    proxy, or None for the generic 500 path. Walks the cause chain for a
    real status/retry-after carrier FIRST (the wrapper's traceback
    string must not shadow a surviving cause's hint), then falls back to
    the remote traceback text for causes that didn't survive pickling.
    Both passes are table-driven off ``exceptions.SERVING_ERRORS``: the
    attr pass reads the ``status_code``/``retryable`` the
    ``@serving_error`` decorator stamped, the traceback pass scans for
    ANY registered class name — adding a typed error to the table is the
    whole job, no proxy ladder to extend."""
    for err in _causes(e):
        code = getattr(err, "status_code", None)
        if code is not None:
            body = {"error": str(err)}
            retry = getattr(err, "retry_after_s", None)
            if retry is not None:
                body["retry_after_s"] = round(float(retry), 3)
            return int(code), body
    for err in _causes(e):
        tb = getattr(err, "tb_str", "")
        if not tb:
            continue
        for name, spec in SERVING_ERRORS.items():
            if name in tb:
                body = {"error": str(err)}
                if spec.retryable:
                    body["retry_after_s"] = 1.0
                return spec.status_code, body
    return None


@dataclass
class AdmissionConfig:
    """Per-replica admission caps. Every cap reads host shadow state;
    each is scaled by the request class's fraction so lower classes shed
    first (``frac``). ``enabled=False`` keeps the controller counting but
    admits everything (the bench's baseline arm)."""

    enabled: bool = True
    # waiting requests (engine admission queue) before shedding
    max_queue_depth: int = 64
    # KV-occupancy cap, measured as BACKLOG: (occupied + queued-demand
    # tokens) / cache token capacity. Queued demand counts prompt +
    # max_tokens, so the ratio keeps growing with the queue — a cache
    # merely full of live sequences (ratio ~1) is healthy, a cache whose
    # backlog is several times its capacity is not.
    max_kv_backlog: float = 4.0
    # estimated queue wait (see AdmissionController.estimate_queue_wait_s)
    max_queue_wait_s: float = 30.0
    # optional headroom reservation: shed class c once slots_in_use /
    # slots_total >= max_slot_occupancy * frac(c). None (default) = off —
    # full slot occupancy is the NORMAL state of a healthy saturated
    # replica. Opt in when latency-sensitive classes must keep decoding
    # without prefill interference from backfilled low-class admissions
    # (the overload bench's protected-streams arm).
    max_slot_occupancy: float | None = None
    # per-class fraction of every cap: priority 0 sheds at frac[0] of
    # each cap, the top class only at the full cap. Priorities beyond
    # the tuple clamp to the last entry.
    class_fracs: tuple = (0.5, 0.75, 1.0)

    def class_index(self, priority: int) -> int:
        """The ONE mapping from raw (client-supplied) priority to the
        clamped class index the caps, counters, and metric labels all
        use — so they can never drift apart."""
        return max(0, min(int(priority), len(self.class_fracs) - 1))

    def frac(self, priority: int) -> float:
        return float(self.class_fracs[self.class_index(priority)])


class AdmissionController:
    """Bounded admission at one serving replica's ingress.

    ``check(priority)`` either returns (admitted) or raises a typed
    ``OverloadedError``/``ReplicaDrainingError``. All inputs are host
    shadow state: ``engine.host_load()`` and the telemetry plane's live
    EMAs (``EngineTelemetry.itl_ema_s`` / ``service_ema_s``, fed by the
    flight recorder's drain-path stamps). Telemetry off (engine built
    with telemetry=False) degrades gracefully: the wait estimate is 0
    and only the depth/backlog caps apply."""

    def __init__(self, engine, cfg: AdmissionConfig | None = None):
        self.engine = engine
        self.cfg = cfg if cfg is not None else AdmissionConfig()
        self._lock = threading.Lock()
        self.counts = {  # guarded-by: _lock
            "admitted": 0, "shed_depth": 0, "shed_backlog": 0,
            "shed_wait": 0, "shed_slots": 0, "shed_draining": 0,
        }
        self.shed_by_class: dict[int, int] = {}  # guarded-by: _lock
        self._draining = False
        # pre-bound metric handles (llm/telemetry.py catalog); shed-class
        # handles bind lazily (class cardinality is tiny)
        self._tel = getattr(engine, "_tel", None)
        self._b_shed: dict[str, object] = {}
        self._b_wait = self._b_drain = None
        if self._tel is not None:
            from ray_tpu.llm.telemetry import instruments

            m = instruments()
            self._m_shed = m["rt_llm_requests_shed_total"]
            self._b_wait = m["rt_llm_admission_queue_wait_est_ms"].bind(self._tel.tags)
            self._b_drain = m["rt_llm_drain_state"].bind(self._tel.tags)
            self._b_drain.set(0.0)
            # keep the wait-estimate gauge LIVE between admissions: the
            # telemetry sample tick refreshes it from the current queue
            # depth (service-path estimate only — the tick runs under
            # the engine lock, so no host_load() re-entry), so the panel
            # decays as the queue drains instead of freezing at its peak
            self._tel.sample_hook = self._refresh_wait_gauge

    def _refresh_wait_gauge(self, queue_depth: int) -> None:
        """Telemetry sample-tick hook: re-estimate from the live queue
        depth without taking the engine lock (on_step already holds it)."""
        if self._b_wait is not None and self._tel is not None:
            est = queue_depth * self._tel.service_ema_s / max(self.engine.max_num_seqs, 1)
            self._b_wait.set(round(est * 1e3, 3))

    # -- drain lifecycle ---------------------------------------------------
    def drain(self) -> None:
        """Stop admitting: every new request sheds with
        ReplicaDrainingError (drain-state gauge -> 1)."""
        self._draining = True
        if self._b_drain is not None:
            self._b_drain.set(1.0)

    def drained(self) -> None:
        """In-flight work finished and resources released (gauge -> 2)."""
        if self._b_drain is not None:
            self._b_drain.set(2.0)

    @property
    def draining(self) -> bool:
        return self._draining

    # -- the admission test ------------------------------------------------
    def estimate_queue_wait_s(self, load: dict | None = None) -> float:
        """Expected time a request admitted NOW spends waiting for a
        slot, from the flight recorder's TWO live EMAs: the queue drains
        one request per slot-turnover (queue_depth x per-request
        service-time EMA) and, independently, must decode its queued
        token demand (live ITL EMA x queued max_tokens) — the max of the
        two paths, divided by the slots draining in parallel. The ITL
        path covers the cold window where nothing has finished yet but
        tokens are already flowing. 0 when telemetry is off or both EMAs
        are still empty."""
        tel = self._tel
        if tel is None:
            return 0.0
        if load is None:
            load = self.engine.host_load()
        service_path = load["queue_depth"] * tel.service_ema_s
        itl_path = load.get("queued_gen_tokens", 0) * tel.itl_ema_s
        return max(service_path, itl_path) / max(load["slots_total"], 1)

    def _shed(self, reason: str, priority: int, est_wait: float):
        # the CLASS (clamped, exactly what the admission arithmetic used)
        # keys the counters and the metric label — raw client-supplied
        # priorities must never mint unbounded label cardinality
        cls_ix = self.cfg.class_index(priority)
        with self._lock:
            self.counts["shed_" + reason] += 1
            self.shed_by_class[cls_ix] = self.shed_by_class.get(cls_ix, 0) + 1
        cls = str(cls_ix)
        if self._tel is not None:
            h = self._b_shed.get(cls)
            if h is None:
                h = self._b_shed[cls] = self._m_shed.bind({**self._tel.tags, "class": cls})
            h.inc(1.0)
        base = min(max(est_wait, 0.25), 30.0)
        retry = base * (1.0 + _retry_jitter.uniform(-RETRY_JITTER_FRAC, RETRY_JITTER_FRAC))
        err_cls = ReplicaDrainingError if reason == "draining" else OverloadedError
        # shed_class carries the CLAMPED class (what the admission
        # arithmetic used) so routers re-counting the shed label it
        # identically to this replica's own metric
        raise err_cls(
            f"replica overloaded ({reason}): request class {priority} shed; "
            f"retry after ~{retry:.2f}s",
            retry_after_s=retry,
            shed_class=cls_ix,
        )

    def check(self, priority: int = 0) -> None:
        """Admit or raise. Reads one host_load() snapshot; updates the
        queue-wait-estimate gauge so the dashboard shows the admission
        plane's view of pressure even between sheds."""
        if self._draining:
            self._shed("draining", priority, 2.0)
        cfg = self.cfg
        load = self.engine.host_load()
        est_wait = self.estimate_queue_wait_s(load)
        if self._b_wait is not None:
            self._b_wait.set(round(est_wait * 1e3, 3))
        if not cfg.enabled:
            with self._lock:
                self.counts["admitted"] += 1
            return
        frac = cfg.frac(priority)
        if load["queue_depth"] >= cfg.max_queue_depth * frac:
            self._shed("depth", priority, est_wait)
        backlog = (load["occupied_tokens"] + load["queued_tokens"]) / max(load["capacity_tokens"], 1)
        if backlog >= cfg.max_kv_backlog * frac:
            self._shed("backlog", priority, est_wait)
        if est_wait >= cfg.max_queue_wait_s * frac:
            self._shed("wait", priority, est_wait)
        if cfg.max_slot_occupancy is not None:
            slot_occ = load["slots_in_use"] / max(load["slots_total"], 1)
            if slot_occ >= cfg.max_slot_occupancy * frac:
                self._shed("slots", priority, est_wait)
        with self._lock:
            self.counts["admitted"] += 1

    def check_capacity(self) -> None:
        """Class-blind admission at the FULL caps — for ingresses that do
        not know the request class (the disagg prefill replica: the
        class-aware shed already ran at the router/decode ingress)."""
        self.check(len(self.cfg.class_fracs) - 1)

    def stats(self) -> dict:
        # estimate BEFORE taking the lock: it may fall through to
        # engine.host_load(), which waits on the ENGINE lock (held for
        # whole serving steps) — computing it under self._lock would stall
        # every ingress check()/record_outcome() behind a step boundary
        wait_est = round(self.estimate_queue_wait_s(), 4)
        with self._lock:
            return {
                **self.counts,
                "shed_by_class": dict(self.shed_by_class),
                "draining": self._draining,
                "queue_wait_est_s": wait_est,
            }


def router_terminal(last, *, budget, priority: int, counters: dict, lock,
                    telemetry=None, shed_msg: str) -> None:
    """The ONE terminal epilogue both routers run when their failover
    loop ends without success (the second half of the shared-budget
    policy — keeping it here means the disagg and kvplane routers can
    never drift):

    - budget exhaustion (vs. the ranked list merely running out on a
      small fleet) counts into ``budget_exhausted`` + the telemetry
      counter;
    - when the LAST failure was itself a shed, the request was gracefully
      load-shed, not broken: count ``shed`` (never ``failed`` — a
      deliberate shedding event must not read as an error-rate spike)
      and RAISE OverloadedError with the replica's dug-out backoff hint;
    - otherwise count ``failed`` + the error-finish metric and RETURN so
      the caller raises its own terminal class.
    """
    if budget.remaining == 0:
        budget.exhaust()
        with lock:
            counters["budget_exhausted"] += 1
    if is_overloaded(last):
        # re-use the shedding replica's CLAMPED class so the router- and
        # replica-stage shed series label the same traffic identically;
        # when the attribute was lost in wire pickling (tb_str-only
        # detection), clamp with the DEFAULT class count — the router
        # cannot know a non-default replica config, but agrees with every
        # default-config replica
        cls = shed_class_of(last, default=AdmissionConfig().class_index(priority))
        with lock:
            counters["shed"] += 1
        if telemetry is not None:
            telemetry.on_shed(cls)
        raise OverloadedError(
            shed_msg, retry_after_s=retry_hint_of(last), shed_class=cls
        ) from last
    with lock:
        counters["failed"] += 1
    if telemetry is not None:
        telemetry.on_failed()


class RetryBudget:
    """Per-request cross-replica failover budget, shared by the disagg
    and kvplane routers (one policy, one exhaustion counter). Every
    ATTEMPT — first try included — spends one unit; ``exhaust()`` is the
    router's terminal-failure hook (counts into
    ``rt_llm_retry_budget_exhausted_total`` when telemetry is wired)."""

    def __init__(self, attempts: int, telemetry=None):
        self.attempts = max(1, int(attempts))
        self.spent = 0
        self._tel = telemetry

    def try_spend(self) -> bool:
        if self.spent >= self.attempts:
            return False
        self.spent += 1
        return True

    @property
    def remaining(self) -> int:
        return self.attempts - self.spent

    def exhaust(self) -> None:
        if self._tel is not None:
            try:
                self._tel.on_budget_exhausted()
            except Exception:  # tpulint: disable=ERR001 — noqa: BLE001 — telemetry accounting is never load-bearing; failing it must not fail the request path
                pass


def wait_for_drain(server, timeout_s: float = 30.0, poll_s: float = 0.02) -> bool:
    """Poll a serving replica's engine until in-flight work settles (the
    drain loop's bounded wait, shared by drain() and tests)."""
    deadline = time.time() + timeout_s
    while server.engine.has_unfinished():
        if time.time() >= deadline:
            return False
        time.sleep(poll_s)
    return True
