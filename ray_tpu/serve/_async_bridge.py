"""Async adapters over the blocking handle API.

Reference parity: the reference's proxy awaits DeploymentResponses
natively on uvicorn's loop (serve/_private/proxy.py). Here the runtime is
thread-based, so two bridges make handle results awaitable WITHOUT a
blocked thread per request:

  * unary: the runtime's seal callback (Runtime.add_done_callback) fires
    on the head's pool and resolves an asyncio future via
    call_soon_threadsafe — no thread waits;
  * streaming: ONE pump thread multiplexes ALL open streams, polling each
    registered generator under the runtime's generator condition and
    pushing ready values into per-stream asyncio queues. Thread count is
    O(1) in the number of concurrent streams — the property that lets the
    async proxy hold hundreds of SSE connections.
"""

from __future__ import annotations

import asyncio
import threading
import time

import ray_tpu
from ray_tpu.core import context
from ray_tpu.exceptions import GetTimeoutError

_SENTINEL = object()


async def result_async(response, timeout_s: float | None = None):
    """Await a DeploymentResponse without blocking a thread."""
    loop = asyncio.get_running_loop()
    fut: asyncio.Future = loop.create_future()

    def cb(value, error):
        def settle():
            if fut.cancelled():
                return
            if error is not None:
                fut.set_exception(error)
            else:
                fut.set_result(value)

        loop.call_soon_threadsafe(settle)

    # async handles bind their replica ref from the dispatcher thread —
    # a loop-side notification (no parked executor thread per pending
    # request) wakes us when it happens; one deadline covers bind + result
    deadline = None if timeout_s is None else loop.time() + timeout_s
    if response._ref is None:
        bind_fut: asyncio.Future = loop.create_future()

        def _on_bind():
            loop.call_soon_threadsafe(lambda: bind_fut.done() or bind_fut.set_result(None))

        if response._add_bind_callback(_on_bind):
            try:
                await asyncio.wait_for(bind_fut, timeout=timeout_s)
            except asyncio.TimeoutError:
                raise GetTimeoutError(f"request still queued after {timeout_s}s") from None
        if response._error is not None:
            raise response._error
    rt = context.get_client()
    rt.add_done_callback(response._ref.id, cb)
    remaining = None if deadline is None else max(0.0, deadline - loop.time())
    try:
        value = await asyncio.wait_for(fut, timeout=remaining)
    except asyncio.TimeoutError:
        raise GetTimeoutError(f"request exceeded {timeout_s}s") from None
    finally:
        if fut.done() and not fut.cancelled():
            response._settle()
    return value


class _StreamPump:
    """Single background thread draining every registered stream."""

    def __init__(self):
        self._lock = threading.Lock()
        self._streams: dict[int, dict] = {}  # id -> state
        self._next = 0
        self._thread: threading.Thread | None = None
        self._stop = False

    def register(self, gen, loop) -> tuple[int, asyncio.Queue]:
        """gen: core ObjectRefGenerator (has .generator_id)."""
        q: asyncio.Queue = asyncio.Queue(maxsize=64)
        with self._lock:
            sid = self._next
            self._next += 1
            self._streams[sid] = {
                "gen_id": gen.generator_id,
                "index": 0,
                "loop": loop,
                "q": q,
                "dead": False,
                # puts scheduled via call_soon_threadsafe but not yet
                # applied on the loop: qsize() alone can't see them, so
                # backpressure counts both (guarded by cnt_lock)
                "inflight": 0,
                "cnt_lock": threading.Lock(),
            }
            if self._thread is None or not self._thread.is_alive():
                self._stop = False
                self._thread = threading.Thread(target=self._run, name="serve-stream-pump", daemon=True)
                self._thread.start()
        return sid, q

    def unregister(self, sid: int):
        with self._lock:
            self._streams.pop(sid, None)

    def _run(self):
        from ray_tpu.core.ids import ObjectID

        rt = context.get_client()
        while not self._stop:
            with self._lock:
                streams = list(self._streams.items())
            if not streams:
                with self._lock:
                    if not self._streams:
                        self._thread = None
                        return
                continue
            progressed = False
            for sid, st in streams:
                if st["dead"]:
                    continue
                # drain whatever is ready for this stream right now
                while True:
                    with st["cnt_lock"]:
                        backlog = st["q"].qsize() + st["inflight"]
                    if backlog >= 48:
                        break  # backpressure: consumer lagging; headroom
                        # below maxsize keeps sentinel/error pushes lossless
                    item_id = st.pop("pending_item", None)
                    if item_id is None:
                        try:
                            item_id = rt.next_generator_item(st["gen_id"], st["index"], timeout=0)
                        except GetTimeoutError:
                            break  # nothing ready yet
                        except Exception as e:  # noqa: BLE001
                            self._push(st, e)
                            st["dead"] = True
                            break
                        if item_id is None:
                            self._push(st, _SENTINEL)
                            st["dead"] = True
                            break
                        st["index"] += 1
                    progressed = True
                    try:
                        # near-zero timeout: a value needing a slow
                        # cross-node pull must not head-of-line block the
                        # SHARED pump — park it and retry next pass while
                        # other streams keep draining
                        value = rt.get_object(item_id, timeout=0.05)
                    except GetTimeoutError:
                        st["pending_item"] = item_id
                        st["pending_since"] = st.get("pending_since") or time.monotonic()
                        if time.monotonic() - st["pending_since"] > 60.0:
                            self._push(st, TimeoutError("stream item fetch stalled >60s"))
                            st["dead"] = True
                        break
                    except BaseException as e:  # noqa: BLE001
                        self._push(st, e)
                        st["dead"] = True
                        break
                    st.pop("pending_since", None)
                    self._push(st, value)
            with self._lock:
                for sid, st in list(self._streams.items()):
                    if st["dead"]:
                        del self._streams[sid]
            if not progressed:
                # sleep on the generator condition: any stream item or
                # finish notifies it, so wakeups track real progress
                with rt._gen_cond:
                    rt._gen_cond.wait(timeout=0.05)

    def _push(self, st, value):
        loop, q = st["loop"], st["q"]

        def put():
            try:
                q.put_nowait(value)
            except asyncio.QueueFull:  # pragma: no cover - inflight accounting prevents this
                pass
            finally:
                with st["cnt_lock"]:
                    st["inflight"] -= 1

        with st["cnt_lock"]:
            st["inflight"] += 1
        try:
            loop.call_soon_threadsafe(put)
        except RuntimeError:
            with st["cnt_lock"]:
                st["inflight"] -= 1
            st["dead"] = True  # loop closed (proxy shutdown)


_pump = _StreamPump()


async def aiter_stream(gen_response, item_timeout_s: float | None = None):
    """Async-iterate a DeploymentResponseGenerator through the shared
    pump; cancels the producer on early exit (client disconnect)."""
    loop = asyncio.get_running_loop()
    sid, q = _pump.register(gen_response._gen, loop)
    try:
        while True:
            item = await asyncio.wait_for(q.get(), timeout=item_timeout_s)
            if item is _SENTINEL:
                gen_response._exhausted = True
                break
            if isinstance(item, BaseException):
                raise item
            yield item
    except asyncio.TimeoutError:
        raise GetTimeoutError(f"stream item exceeded {item_timeout_s}s") from None
    finally:
        _pump.unregister(sid)
        gen_response._settle()
