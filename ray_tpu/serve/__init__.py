"""ray_tpu.serve — model serving on the ray_tpu runtime.

Controller + replica state machine + pow-2 routing + request-metric
autoscaling + HTTP proxy (reference: python/ray/serve). TPU-native twist:
replicas pin TPU resources and keep a warm JAX engine (see
ray_tpu.serve.llm for the LLM deployment builder).
"""

from ray_tpu.util.usage import record_library_usage as _rlu

_rlu("serve")

from ray_tpu.serve.api import (
    delete,
    get_app_handle,
    get_deployment_handle,
    run,
    shutdown,
    start,
    status,
)
from ray_tpu.serve.batching import batch
from ray_tpu.serve.multiplex import get_multiplexed_model_id, multiplexed
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig, HTTPOptions, ReplicaConfig
from ray_tpu.serve.deployment import Application, Deployment, deployment
from ray_tpu.serve.handle import DeploymentHandle, DeploymentResponse, DeploymentResponseGenerator
from ray_tpu.serve._proxy import Request

__all__ = [
    "Application",
    "AutoscalingConfig",
    "Deployment",
    "DeploymentConfig",
    "DeploymentHandle",
    "DeploymentResponse",
    "DeploymentResponseGenerator",
    "HTTPOptions",
    "ReplicaConfig",
    "Request",
    "batch",
    "get_multiplexed_model_id",
    "multiplexed",
    "delete",
    "deployment",
    "get_app_handle",
    "get_deployment_handle",
    "run",
    "shutdown",
    "start",
    "status",
]
