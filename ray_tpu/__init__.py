"""ray_tpu: a TPU-native distributed AI runtime.

Task/actor runtime + cluster scheduling (placement groups, TPU slice gang
reservation) + collective communication over JAX/XLA meshes + libraries:
data (streaming datasets), train (JaxTrainer/GSPMD), tune (HPO), rllib (RL),
serve (model serving), llm (batched LLM inference).

Built new for TPU (JAX/XLA/pjit/Pallas over ICI+DCN) with the capabilities
of the reference Ray codebase; see SURVEY.md for the blueprint mapping.
"""

from ray_tpu import exceptions  # noqa: F401
from ray_tpu.api import (  # noqa: F401
    ActorClass,
    ActorHandle,
    RemoteFunction,
    available_resources,
    cancel,
    cluster_resources,
    get,
    get_actor,
    get_runtime_context,
    init,
    internal_free,
    is_initialized,
    kill,
    method,
    nodes,
    put,
    remote,
    shutdown,
    wait,
)
from ray_tpu.core.object_ref import ObjectRef, ObjectRefGenerator  # noqa: F401
from ray_tpu.util.timeline import timeline  # noqa: F401

__version__ = "0.1.0"

_LAZY_SUBMODULES = ("data", "train", "tune", "rllib", "serve", "llm", "collective", "workflow")


def __getattr__(name):
    if name in _LAZY_SUBMODULES:
        import importlib

        mod = importlib.import_module(f"ray_tpu.{name}")
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'ray_tpu' has no attribute {name!r}")
