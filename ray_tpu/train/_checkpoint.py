"""Checkpoint: a directory handle with filesystem-URI persistence.

Reference parity: python/ray/train/_checkpoint.py — Checkpoint is a
(path, filesystem) pair; from_directory/to_directory/as_directory; metrics
ride alongside. Storage here is a local/NFS path (pyarrow-fs URIs can be
added at the storage layer); sharded JAX array checkpoints go through
ray_tpu.train.jax_checkpoint (orbax-style per-shard async save).
"""

from __future__ import annotations

import contextlib
import json
import os
import shutil
import tempfile
import uuid


class Checkpoint:
    def __init__(self, path: str):
        self.path = str(path)

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(path)

    def to_directory(self, path: str | None = None) -> str:
        """Materialize into `path` (copy); returns the directory."""
        if path is None:
            path = tempfile.mkdtemp(prefix="rt_ckpt_")
        if os.path.abspath(path) != os.path.abspath(self.path):
            shutil.copytree(self.path, path, dirs_exist_ok=True)
        return path

    @contextlib.contextmanager
    def as_directory(self):
        """Context manager giving a local directory view (no copy when the
        checkpoint is already local)."""
        yield self.path

    def update_metadata(self, metadata: dict):
        meta = self.get_metadata()
        meta.update(metadata)
        with open(os.path.join(self.path, ".metadata.json"), "w") as f:
            json.dump(meta, f)

    def get_metadata(self) -> dict:
        p = os.path.join(self.path, ".metadata.json")
        if os.path.exists(p):
            with open(p) as f:
                return json.load(f)
        return {}

    def __repr__(self):
        return f"Checkpoint(path={self.path!r})"

    def __eq__(self, other):
        return isinstance(other, Checkpoint) and os.path.abspath(self.path) == os.path.abspath(other.path)


class CheckpointManager:
    """Top-k checkpoint retention keyed on a score attribute.

    Reference parity: train/v2/_internal/execution/checkpoint/
    checkpoint_manager.py (register_checkpoint, top-k eviction) — the
    controller-side arbiter; workers upload, rank 0's metrics score.
    """

    def __init__(self, run_dir: str, config=None):
        from ray_tpu.train.config import CheckpointConfig

        self.run_dir = run_dir
        self.config = config or CheckpointConfig()
        self._tracked: list[tuple[float | None, int, Checkpoint, dict]] = []
        self._seq = 0
        os.makedirs(run_dir, exist_ok=True)

    def new_checkpoint_dir(self, name: str | None = None) -> str:
        self._seq += 1
        name = name or f"checkpoint_{self._seq:06d}_{uuid.uuid4().hex[:6]}"
        d = os.path.join(self.run_dir, name)
        os.makedirs(d, exist_ok=True)
        return d

    def register_checkpoint(self, checkpoint: Checkpoint, metrics: dict) -> Checkpoint:
        score = None
        attr = self.config.checkpoint_score_attribute
        if attr is not None and attr in (metrics or {}):
            score = float(metrics[attr])
        self._tracked.append((score, self._seq, checkpoint, dict(metrics or {})))
        self._evict()
        return checkpoint

    def _evict(self):
        k = self.config.num_to_keep
        if k is None or len(self._tracked) <= k:
            return
        sign = 1.0 if self.config.checkpoint_score_order == "max" else -1.0

        def keep_rank(entry):
            score, seq, _, _ = entry
            # unscored checkpoints fall back to recency
            return (0, sign * score) if score is not None else (-1, seq)

        latest = self._tracked[-1]  # never delete the most recent (resume anchor)
        ranked = sorted(self._tracked[:-1], key=keep_rank, reverse=True)
        keep = ranked[: k - 1] + [latest]
        for score, seq, ckpt, _ in self._tracked:
            if all(c is not ckpt for _, _, c, _ in keep):
                shutil.rmtree(ckpt.path, ignore_errors=True)
        self._tracked = [e for e in self._tracked if any(e[2] is c for _, _, c, _ in keep)]

    @property
    def latest_checkpoint(self) -> Checkpoint | None:
        return self._tracked[-1][2] if self._tracked else None

    @property
    def best_checkpoint(self) -> Checkpoint | None:
        scored = [e for e in self._tracked if e[0] is not None]
        if not scored:
            return self.latest_checkpoint
        sign = 1.0 if self.config.checkpoint_score_order == "max" else -1.0
        return max(scored, key=lambda e: sign * e[0])[2]

    def best_checkpoints(self) -> list[tuple[Checkpoint, dict]]:
        return [(c, m) for _, _, c, m in self._tracked]
