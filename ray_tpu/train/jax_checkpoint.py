"""Sharded JAX checkpoint save/restore (orbax-backed, msgpack fallback).

TPU-native equivalent of the reference's torch state-dict checkpoints
(SURVEY.md §5.4): each host writes only its addressable shards (orbax
OCDBT), restore re-shards onto the current mesh — so checkpoints survive
topology changes. Async save returns immediately and the commit happens on
the next report barrier.
"""

from __future__ import annotations

import os


def _orbax():
    import orbax.checkpoint as ocp

    return ocp


def save_pytree(path: str, tree, *, async_save: bool = False):
    """Save a pytree of jax.Arrays (sharded or not) into `path`."""
    ocp = _orbax()
    path = os.path.abspath(path)
    ckptr = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler()) if async_save else ocp.Checkpointer(
        ocp.PyTreeCheckpointHandler()
    )
    ckptr.save(os.path.join(path, "state"), tree, force=True)
    if async_save:
        return ckptr  # caller must .wait_until_finished() before commit
    return None


def restore_pytree(path: str, *, target=None, shardings=None):
    """Restore; with `shardings` (a pytree of NamedSharding) arrays land
    directly on-device with the requested layout."""
    ocp = _orbax()
    import jax

    ckptr = ocp.Checkpointer(ocp.PyTreeCheckpointHandler())
    item = os.path.join(os.path.abspath(path), "state")
    if shardings is not None:
        abstract = jax.tree.map(
            lambda s, t: jax.ShapeDtypeStruct(t.shape, t.dtype, sharding=s),
            shardings,
            target,
        )
        args = ocp.args.PyTreeRestore(item=abstract) if hasattr(ocp.args, "PyTreeRestore") else None
        try:
            return ckptr.restore(item, item=abstract)
        except TypeError:
            return ckptr.restore(item, args=args)
    return ckptr.restore(item, item=target)
