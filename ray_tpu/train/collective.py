"""Train-scoped collectives: barrier + broadcast_from_rank_zero.

Reference parity: train/collective/collectives.py:16,59 — control-plane
collectives between train workers (NOT the ICI data plane; those are XLA
collectives inside the jitted step). Implemented on the group rendezvous
actor from ray_tpu.collective.
"""

from __future__ import annotations

import ray_tpu.collective as col
from ray_tpu.train import context as _ctx

_GROUP = "_rt_train"


def _ensure_group():
    ctx = _ctx.get_context()
    if ctx is None:
        raise RuntimeError("train collectives must be called inside a train worker")
    # attempt_uid keeps the rendezvous actor name unique per worker-group
    # attempt, so a restarted group never collides with the (detached)
    # actor of a failed attempt
    name = f"{_GROUP}:{ctx.get_experiment_name()}:{ctx._attempt_uid}"
    try:
        col.get_rank(name)
    except KeyError:
        col.init_collective_group(ctx.get_world_size(), ctx.get_world_rank(), "object_store", name)
    return name


def group_name_for_attempt(experiment_name: str, attempt_uid: str) -> str:
    """Controller-side name of the per-attempt train collective group."""
    return f"{_GROUP}:{experiment_name}:{attempt_uid}"


def barrier():
    """Block until every train worker reaches the barrier."""
    col.barrier(_ensure_group())


def broadcast_from_rank_zero(data):
    """Rank 0's `data` is returned on every worker."""
    import numpy as np

    name = _ensure_group()
    ctx = _ctx.get_context()
    payload = np.frombuffer(_pickle(data), dtype=np.uint8) if ctx.get_world_rank() == 0 else np.zeros(0, np.uint8)
    out = col.broadcast(payload, src_rank=0, group_name=name)
    return _unpickle(bytes(bytearray(out)))


def _pickle(obj) -> bytes:
    import pickle

    return pickle.dumps(obj)


def _unpickle(b: bytes):
    import pickle

    return pickle.loads(b)


def allreduce(array):
    """Elementwise SUM-allreduce of a numpy array across the train worker
    group (reference: the rabit allreduce xgboost's hist method rides in
    train/xgboost; here the GBDT trainer's histogram sync)."""
    return col.allreduce(array, group_name=_ensure_group())
