"""TrainController: the training run state machine.

Reference parity: train/v2/_internal/execution/controller/controller.py:100
— poll workers, consume report rounds (rank-0-arbitrated checkpoint commit,
reference: report_handler.py + checkpoint_manager.py), apply FailurePolicy
(failure_handling/default.py: RETRY = recreate the whole worker group and
restore from the latest committed checkpoint — the right semantics for TPU
slices, where a dead host invalidates the whole ICI mesh; SURVEY.md §5.3).
"""

from __future__ import annotations

import logging
import os
import shutil
import time

import ray_tpu
from ray_tpu.train._checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.errors import TrainingFailedError
from ray_tpu.train.result import Result
from ray_tpu.train.worker_group import WorkerGroup

logger = logging.getLogger(__name__)

POLL_INTERVAL_S = float(os.environ.get("RT_TRAIN_POLL_INTERVAL_S", "0.05"))


class _ResizeRestart(Exception):
    """Internal signal: the scaling policy requested a new group size —
    restart at the boundary (checkpoint-resume), not a failure."""

    def __init__(self, num_workers: int, reason: str):
        super().__init__(f"resize to {num_workers} workers: {reason}")
        self.num_workers = num_workers


class TrainController:
    def __init__(
        self,
        train_fn,
        train_fn_config,
        scaling_config,
        run_config,
        backend_config,
        datasets: dict | None = None,
        scaling_policy=None,
    ):
        from ray_tpu.train.scaling_policy import FixedScalingPolicy

        self.train_fn = train_fn
        self.train_fn_config = train_fn_config
        self.scaling = scaling_config
        self.scaling_policy = scaling_policy or FixedScalingPolicy(scaling_config)
        self.run_config = run_config
        self.backend_config = backend_config
        self.backend = backend_config.backend_cls()
        self.datasets = datasets or {}
        self.run_dir = os.path.join(run_config.storage_path, run_config.name)
        self.ckpt_manager = CheckpointManager(self.run_dir, run_config.checkpoint_config)
        self.metrics_history: list[dict] = []
        self.resume_checkpoint = None  # user-provided seed; never evicted
        self._restarts = 0
        # RunConfig.callbacks (tune.callbacks API): the whole run logs as
        # one pseudo-trial keyed by the experiment name
        self._callbacks = list(getattr(run_config, "callbacks", None) or [])
        if self._callbacks:
            from types import SimpleNamespace

            self._cb_trial = SimpleNamespace(trial_id=run_config.name, config=dict(train_fn_config or {}))
            for cb in self._callbacks:
                try:
                    cb.setup(self.run_dir)
                except Exception:
                    pass

    # ---------------- main entry ----------------
    def run(self) -> Result:
        import dataclasses

        max_failures = self.run_config.failure_config.max_failures
        while True:
            # the scaling policy sizes each attempt (elastic policies fit
            # the current cluster; reference: scaling_policy.py:29)
            n = self.scaling_policy.workers_for_attempt()
            attempt_scaling = (
                dataclasses.replace(self.scaling, num_workers=n) if n != self.scaling.num_workers else self.scaling
            )
            group = WorkerGroup(
                attempt_scaling,
                self.run_config.name,
                env_vars=getattr(self.backend_config, "env_vars", None),
            )
            try:
                error = self._run_attempt(group)
            finally:
                try:
                    self.backend.on_shutdown(group, self.backend_config)
                except Exception:
                    pass
                group.shutdown()
                if group.attempt_uid is not None:
                    # reap this attempt's detached train-collective actor
                    from ray_tpu.collective.collective import cleanup_group_actor
                    from ray_tpu.train.collective import group_name_for_attempt

                    cleanup_group_actor(group_name_for_attempt(self.run_config.name, group.attempt_uid))
            if isinstance(error, _ResizeRestart):
                # elastic boundary: recompile against the new topology and
                # resume from the latest committed checkpoint. Not a
                # failure — doesn't consume the restart budget.
                logger.info("elastic resize: %s", error)
                continue
            if error is None:
                self._finish_callbacks()
                latest = self.ckpt_manager.latest_checkpoint
                return Result(
                    metrics=self.metrics_history[-1] if self.metrics_history else None,
                    checkpoint=latest,
                    path=self.run_dir,
                    metrics_history=self.metrics_history,
                    best_checkpoints=self.ckpt_manager.best_checkpoints(),
                )
            self._restarts += 1
            if max_failures >= 0 and self._restarts > max_failures:
                return Result(
                    metrics=self.metrics_history[-1] if self.metrics_history else None,
                    checkpoint=self.ckpt_manager.latest_checkpoint,
                    path=self.run_dir,
                    error=TrainingFailedError(
                        f"training failed after {self._restarts - 1} restart(s)", error
                    ),
                    metrics_history=self.metrics_history,
                    best_checkpoints=self.ckpt_manager.best_checkpoints(),
                )
            logger.warning(
                "worker group failed (%s); restart %d/%s from %s",
                error,
                self._restarts,
                max_failures if max_failures >= 0 else "inf",
                self.ckpt_manager.latest_checkpoint,
            )

    # ---------------- one worker-group attempt ----------------
    def _run_attempt(self, group: WorkerGroup):
        latest = self.ckpt_manager.latest_checkpoint or self.resume_checkpoint
        group.start(
            latest_checkpoint_path=latest.path if latest else None,
            dataset_split_fn=self._split_datasets,
        )
        self.backend.on_start(group, self.backend_config)
        self.backend.on_training_start(group, self.backend_config)

        run_refs = group.run_train_async(self.train_fn, self.train_fn_config)
        pending_rounds: dict[int, dict[int, dict]] = {}  # seq -> rank -> report
        state = {"committed": 0}
        done = [False] * len(group)

        from ray_tpu.train.scaling_policy import ResizeDecision

        while not all(done):
            ready, _ = ray_tpu.wait(run_refs, num_returns=len(run_refs), timeout=POLL_INTERVAL_S)
            try:
                self._drain_and_commit(group, pending_rounds, state)
            except Exception as e:  # worker died hard
                return e
            for ref in ready:
                i = run_refs.index(ref)
                if not done[i]:
                    try:
                        ray_tpu.get(ref)
                        done[i] = True
                    except Exception as e:
                        return e
            # resize only between rounds of a still-running group: a
            # decision landing after completion must not discard the
            # finished attempt
            if not all(done):
                decision = self.scaling_policy.poll_running(len(group))
                if isinstance(decision, ResizeDecision) and decision.num_workers != len(group):
                    return _ResizeRestart(decision.num_workers, decision.reason)
        # drain any reports that landed after the loop observed completion
        try:
            self._drain_and_commit(group, pending_rounds, state)
        except Exception:
            pass
        return None

    def _drain_and_commit(self, group, pending_rounds, state):
        """Poll all workers; commit every round (in order) that every rank
        has reached."""
        polls = group.poll()
        for rank, p in enumerate(polls):
            for rep in p["reports"]:
                pending_rounds.setdefault(rep["seq"], {})[rank] = rep
        nxt = state["committed"] + 1
        while len(pending_rounds.get(nxt, ())) == len(group):
            self._commit_round(pending_rounds.pop(nxt))
            state["committed"] = nxt
            nxt += 1

    # ---------------- checkpoint commit ----------------
    def _commit_round(self, rank_reports: dict[int, dict]):
        """Metrics from rank 0; checkpoint = union of every rank's files
        (rank 0 wins name clashes) so sharded per-host checkpoints (orbax
        per-shard writes) land in one directory."""
        metrics = dict(rank_reports[0]["metrics"])
        ckpt = None
        if any(r["checkpoint_path"] for r in rank_reports.values()):
            dest = self.ckpt_manager.new_checkpoint_dir(rank_reports[0].get("checkpoint_dir_name"))
            for rank in sorted(rank_reports, reverse=True):  # rank 0 last => wins
                src = rank_reports[rank]["checkpoint_path"]
                if src and os.path.isdir(src):
                    shutil.copytree(src, dest, dirs_exist_ok=True)
            ckpt = Checkpoint(dest)
            self.ckpt_manager.register_checkpoint(ckpt, metrics)
            metrics["checkpoint_dir_name"] = os.path.basename(dest)
        metrics.setdefault("training_iteration", len(self.metrics_history) + 1)
        metrics["timestamp"] = time.time()
        self.metrics_history.append(metrics)
        for cb in self._callbacks:
            try:
                cb.log_trial_result(self._cb_trial, metrics)
            except Exception:
                pass

    def _finish_callbacks(self):
        for cb in self._callbacks:
            try:
                cb.log_trial_end(self._cb_trial)
                cb.on_experiment_end([self._cb_trial])
            except Exception:
                pass

    def _split_datasets(self, n: int):
        if not self.datasets:
            return [None] * n
        shards = [dict() for _ in range(n)]
        for name, ds in self.datasets.items():
            if hasattr(ds, "streaming_split"):
                for i, piece in enumerate(ds.streaming_split(n)):
                    shards[i][name] = piece
            else:
                for i in range(n):
                    shards[i][name] = ds
        return shards
