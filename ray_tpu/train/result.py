"""Result returned by Trainer.fit() (reference: python/ray/air/result.py)."""

from __future__ import annotations

from dataclasses import dataclass, field

from ray_tpu.train._checkpoint import Checkpoint


@dataclass
class Result:
    metrics: dict | None
    checkpoint: Checkpoint | None
    path: str | None
    error: BaseException | None = None
    metrics_history: list = field(default_factory=list)
    best_checkpoints: list = field(default_factory=list)
    # the trial's resolved param config (tune results; None for train,
    # matching the reference's Result.config)
    config: dict | None = None

    def get_best_checkpoint(self, metric: str, mode: str = "max") -> Checkpoint | None:
        best, best_v = None, None
        for ckpt, m in self.best_checkpoints:
            if metric not in m:
                continue
            v = float(m[metric])
            if best_v is None or (v > best_v if mode == "max" else v < best_v):
                best, best_v = ckpt, v
        return best
