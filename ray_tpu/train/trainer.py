"""Trainers: DataParallelTrainer, JaxTrainer, TorchTrainer.

Reference parity: train/v2/api/data_parallel_trainer.py (fit() spawning the
controller) and train/v2/jax/jax_trainer.py:19 (JaxTrainer = DP trainer
with the JAX backend + TPU slice scaling). The controller runs in the
driver process here (in-process control loop; the reference runs it in an
actor — same topology, fewer hops).
"""

from __future__ import annotations

from ray_tpu.train.backend import BackendConfig, JaxConfig, TorchConfig
from ray_tpu.train.config import RunConfig, ScalingConfig
from ray_tpu.train.controller import TrainController
from ray_tpu.train.errors import TrainingFailedError
from ray_tpu.train.result import Result


class DataParallelTrainer:
    _default_backend_config_cls = BackendConfig

    def __init__(
        self,
        train_loop_per_worker,
        *,
        train_loop_config: dict | None = None,
        scaling_config: ScalingConfig | None = None,
        run_config: RunConfig | None = None,
        backend_config: BackendConfig | None = None,
        datasets: dict | None = None,
        resume_from_checkpoint=None,
        scaling_policy=None,
    ):
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.backend_config = backend_config or self._default_backend_config_cls()
        self.datasets = datasets
        self.resume_from_checkpoint = resume_from_checkpoint
        # elastic training (reference: scaling_policy.py:29): resize the
        # worker group at restart boundaries as cluster capacity changes
        self.scaling_policy = scaling_policy

    def fit(self, raise_on_error: bool = True) -> Result:
        import ray_tpu

        if not ray_tpu.is_initialized():
            ray_tpu.init()
        controller = TrainController(
            self.train_loop_per_worker,
            self.train_loop_config,
            self.scaling_config,
            self.run_config,
            self.backend_config,
            datasets=self.datasets,
            scaling_policy=self.scaling_policy,
        )
        if self.resume_from_checkpoint is not None:
            # seed only — never registered with the manager, so top-k
            # eviction can't delete the user's directory
            controller.resume_checkpoint = self.resume_from_checkpoint
        result = controller.run()
        if result.error is not None and raise_on_error:
            raise result.error
        return result


class JaxTrainer(DataParallelTrainer):
    """SPMD TPU training (reference: train/v2/jax/jax_trainer.py:19).

    The worker group maps 1:1 onto TPU slice hosts; on_start boots the JAX
    coordination service; the user loop builds its mesh with
    ray_tpu.parallel.create_mesh and steps under pjit/GSPMD.
    """

    _default_backend_config_cls = JaxConfig


class TorchTrainer(DataParallelTrainer):
    """CPU/parity trainer with a torch.distributed gloo process group
    (reference: train/torch/torch_trainer.py)."""

    _default_backend_config_cls = TorchConfig


__all__ = ["DataParallelTrainer", "JaxTrainer", "TorchTrainer", "TrainingFailedError"]
