"""WorkerGroup: one training-worker actor per host.

Reference parity: train/v2/_internal/execution/worker_group/
worker_group.py:104 (actor creation on a placement group, SPREAD per host)
+ thread_runner.py (user loop in a thread so the actor stays pollable).
TPU path: placement goes through SlicePlacementGroup gang reservation
(util/tpu.py:52 semantics) so the group owns a whole slice.
"""

from __future__ import annotations

import os
import queue
import threading
import traceback

import ray_tpu
from ray_tpu.train import context as _ctx
from ray_tpu.train._checkpoint import Checkpoint


@ray_tpu.remote(max_concurrency=8)
class TrainWorker:
    """One per host. The user train loop runs in a dedicated thread
    (reference: thread_runner.py) so poll()/execute() stay responsive."""

    def __init__(self, world_rank: int, env_vars: dict | None = None):
        self.world_rank = world_rank
        for k, v in (env_vars or {}).items():
            os.environ[k] = str(v)
        self._reports: queue.Queue = queue.Queue()
        self._thread = None
        self._status = "idle"
        self._error = None

    def setup_context(
        self,
        world_size: int,
        local_rank: int,
        local_world_size: int,
        node_rank: int,
        experiment_name: str,
        latest_checkpoint_path: str | None,
        dataset_shards: dict | None = None,
        attempt_uid: str = "0",
    ):
        ckpt = Checkpoint(latest_checkpoint_path) if latest_checkpoint_path else None
        ctx = _ctx.TrainContext(
            world_size=world_size,
            world_rank=self.world_rank,
            local_rank=local_rank,
            local_world_size=local_world_size,
            node_rank=node_rank,
            experiment_name=experiment_name,
            report_fn=self._on_report,
            latest_checkpoint=ckpt,
            dataset_shards=dataset_shards,
            attempt_uid=attempt_uid,
        )
        _ctx.set_context(ctx)
        return True

    def _on_report(self, seq, metrics, checkpoint, checkpoint_dir_name):
        self._reports.put(
            {
                "seq": seq,
                "metrics": metrics,
                "checkpoint_path": checkpoint.path if checkpoint is not None else None,
                "checkpoint_dir_name": checkpoint_dir_name,
            }
        )

    def execute_fn(self, fn, *args, **kwargs):
        """Run an arbitrary callable in the worker process (backend hooks)."""
        return fn(*args, **kwargs)

    def run_train_fn(self, train_fn, config):
        """Blocking: runs the user loop in a thread, joins it, re-raises."""
        import inspect

        self._status = "running"

        def target():
            try:
                sig = inspect.signature(train_fn)
                if len(sig.parameters) == 0:
                    train_fn()
                else:
                    train_fn(config or {})
                self._status = "finished"
            except BaseException as e:  # noqa: BLE001
                self._error = (e, traceback.format_exc())
                self._status = "error"

        self._thread = threading.Thread(target=target, name="rt-train-loop", daemon=True)  # tpulint: disable=CCR005 — joined two lines down; writes are sequenced-before the join's return
        self._thread.start()
        self._thread.join()
        if self._status == "error":
            e, tb = self._error
            raise RuntimeError(f"train loop failed on rank {self.world_rank}:\n{tb}") from e
        return self.world_rank

    def poll(self):
        """Drain pending reports (called by the controller every tick)."""
        out = []
        while True:
            try:
                out.append(self._reports.get_nowait())
            except queue.Empty:
                break
        return {"status": self._status, "reports": out}

    def node_info(self):
        ctx = ray_tpu.get_runtime_context()
        nid = getattr(ctx, "node_id", None)
        return {"node_id": str(nid) if nid is not None else None, "pid": os.getpid()}


class WorkerGroup:
    def __init__(self, scaling_config, experiment_name: str, env_vars: dict | None = None):
        self.scaling = scaling_config
        self.experiment_name = experiment_name
        self.env_vars = env_vars
        self.workers: list = []
        self._slice_pg = None
        self._pg = None
        self.num_workers = scaling_config.num_workers
        self.attempt_uid = None  # set per start(); scopes per-attempt named actors

    def __len__(self):
        return len(self.workers)

    def _trial_placement_group(self):
        """The enclosing Tune trial's gang reservation, when its bundle
        count covers this group's workers (bundle 0 is the trial driver)."""
        import os

        pg_hex = os.environ.get("RT_TRIAL_PG")
        if not pg_hex:
            return None
        from ray_tpu.core.ids import PlacementGroupID
        from ray_tpu.util.placement_group import PlacementGroup

        pg = PlacementGroup(PlacementGroupID.from_hex(pg_hex))
        specs = pg.bundle_specs
        if len(specs) < self.num_workers + 1:
            # falling back to an own group here would double-book: the
            # trial's gang stays reserved while a second group queues —
            # deadlock on a trial-sized cluster. Fail fast instead.
            raise ValueError(
                f"trial placement group has {len(specs)} bundles but the worker group needs "
                f"{self.num_workers + 1} (driver + workers); size the PlacementGroupFactory "
                "to the trainer's maximum worker count"
            )
        res = self.scaling._worker_resources
        for b in specs[1 : self.num_workers + 1]:
            if any(b.get(k, 0) < v for k, v in res.items() if v > 0):
                # a too-small bundle would leave the worker unschedulable
                # forever (bundle allocation never succeeds): fail fast
                raise ValueError(
                    f"trial placement group bundle {b} cannot fit worker resources {res}; "
                    "size the PlacementGroupFactory worker bundles to the trainer's "
                    "resources_per_worker"
                )
        return pg

    # ---------------- lifecycle ----------------
    def start(self, latest_checkpoint_path: str | None = None, dataset_split_fn=None):
        sc = self.scaling
        actor_opts = []
        if sc.use_tpu and sc.topology:
            from ray_tpu.util.tpu import SlicePlacementGroup

            self._slice_pg = SlicePlacementGroup(sc.topology, sc.accelerator_version)
            self._slice_pg.wait()
            self.num_workers = self._slice_pg.num_hosts
            for i in range(self.num_workers):
                actor_opts.append(
                    dict(
                        num_tpus=self._slice_pg.chips_per_host,
                        placement_group=self._slice_pg.placement_group,
                        placement_group_bundle_index=i,
                    )
                )
        else:
            res = sc._worker_resources
            from ray_tpu.util.placement_group import placement_group

            trial_pg = self._trial_placement_group()
            if trial_pg is not None:
                # running inside a Tune trial with a gang reservation:
                # workers go into the trial PG's bundles 1..N instead of
                # reserving a second group (reference: tune trials as
                # PlacementGroupFactory with trainer worker bundles)
                pg, owned = trial_pg, False
            else:
                bundles = [dict(res) for _ in range(self.num_workers)]
                pg, owned = placement_group(bundles, strategy=sc.placement_strategy), True
                pg.wait()
            self._pg = pg if owned else None  # only owned groups are removed at stop
            for i in range(self.num_workers):
                opts = dict(
                    num_cpus=res.get("CPU", 1),
                    placement_group=pg,
                    placement_group_bundle_index=i + (0 if owned else 1),
                )
                if res.get("TPU"):
                    opts["num_tpus"] = res["TPU"]
                extra = {k: v for k, v in res.items() if k not in ("CPU", "TPU")}
                if extra:
                    opts["resources"] = extra
                actor_opts.append(opts)

        import uuid

        self.attempt_uid = uuid.uuid4().hex[:8]
        self.workers = [
            TrainWorker.options(**opts).remote(world_rank=i, env_vars=self.env_vars)
            for i, opts in enumerate(actor_opts)
        ]
        # local ranks: workers sharing a node get consecutive local ranks
        infos = ray_tpu.get([w.node_info.remote() for w in self.workers])
        by_node: dict = {}
        local_ranks, node_ranks = [], []
        for info in infos:
            node = info["node_id"] or "local"
            node_rank = list(by_node).index(node) if node in by_node else len(by_node)
            lr = by_node.setdefault(node, 0)
            by_node[node] += 1
            local_ranks.append(lr)
            node_ranks.append(node_rank)
        # dataset shards are split only once the true worker count is known
        # (the TPU slice path derives num_workers from the slice host count)
        shards = dataset_split_fn(self.num_workers) if dataset_split_fn else [None] * self.num_workers
        ray_tpu.get(
            [
                w.setup_context.remote(
                    self.num_workers,
                    local_ranks[i],
                    by_node[infos[i]["node_id"] or "local"],
                    node_ranks[i],
                    self.experiment_name,
                    latest_checkpoint_path,
                    shards[i],
                    self.attempt_uid,
                )
                for i, w in enumerate(self.workers)
            ]
        )

    def shutdown(self):
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
        self.workers = []
        if self._pg is not None:
            from ray_tpu.util.placement_group import remove_placement_group

            try:
                remove_placement_group(self._pg)
            except Exception:
                pass
            self._pg = None
        if self._slice_pg is not None:
            try:
                self._slice_pg.remove()
            except Exception:
                pass
            self._slice_pg = None

    # ---------------- execution ----------------
    def execute_async(self, fn, *args, **kwargs):
        return [w.execute_fn.remote(fn, *args, **kwargs) for w in self.workers]

    def execute(self, fn, *args, **kwargs):
        return ray_tpu.get(self.execute_async(fn, *args, **kwargs))

    def execute_single(self, rank: int, fn, *args, **kwargs):
        return ray_tpu.get(self.workers[rank].execute_fn.remote(fn, *args, **kwargs))

    def run_train_async(self, train_fn, config):
        return [w.run_train_fn.remote(train_fn, config) for w in self.workers]

    def poll(self):
        return ray_tpu.get([w.poll.remote() for w in self.workers])
