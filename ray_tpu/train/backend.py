"""Backend plugin interface + JAX and Torch backends.

Reference parity: python/ray/train/backend.py (Backend/BackendConfig) and
train/v2/jax/config.py:56-96 (_JaxBackend.on_start running
``jax.distributed.initialize(coordinator, num_workers, index)`` on every
worker) — the TPU-native path. TorchConfig mirrors train/torch/config.py
(TCP rendezvous + gloo) for CPU-parity workloads.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class BackendConfig:
    # set in every worker process before the user loop imports jax/torch
    # (e.g. {"LIBTPU_INIT_ARGS": ...}, XLA flags)
    env_vars: dict | None = None

    @property
    def backend_cls(self):
        return Backend


class Backend:
    """Hooks called by the controller around worker-group lifecycle."""

    def on_start(self, worker_group, backend_config):
        pass

    def on_training_start(self, worker_group, backend_config):
        pass

    def on_shutdown(self, worker_group, backend_config):
        pass


# ----------------------------------------------------------------------
# JAX backend (the primary one)
# ----------------------------------------------------------------------
@dataclass
class JaxConfig(BackendConfig):
    """TPU/JAX distributed bootstrap.

    distributed: "auto" initializes jax.distributed when num_workers > 1
    (coordination service over DCN; XLA then compiles collectives onto
    ICI), "never" skips (single host / tests), "always" forces.
    """

    distributed: str = "auto"

    @property
    def backend_cls(self):
        return _JaxBackend


class _JaxBackend(Backend):
    def on_start(self, worker_group, backend_config: "JaxConfig"):
        n = len(worker_group)
        mode = backend_config.distributed
        if mode == "never" or (mode == "auto" and n <= 1):
            return
        # coordinator = worker 0's host (slice worker 0 per the reference's
        # TPU topology: tpu.py worker-id labels); pick a free port there.
        # When every worker reports the same hostname the job is single-
        # machine (shm-isolated test nodes included): use loopback, which
        # is the only iface guaranteed reachable across its processes.
        hostnames = worker_group.execute(_get_hostname)
        if len(set(hostnames)) == 1:
            host, port = worker_group.execute_single(0, _free_coordinator_addr, loopback=True)
        else:
            host, port = worker_group.execute_single(0, _free_coordinator_addr)
        coordinator = f"{host}:{port}"
        worker_group.execute(_init_jax_distributed, coordinator, n)

    def on_shutdown(self, worker_group, backend_config):
        try:
            worker_group.execute(_shutdown_jax_distributed)
        except Exception:
            pass


def _get_hostname():
    import socket

    return socket.gethostname()


def _free_coordinator_addr(loopback: bool = False):
    """Runs ON worker 0: its routable IP + a free port (other hosts of the
    slice must be able to dial it — 127.0.0.1 would only work single-host).
    Candidate interfaces are VERIFIED by a loopback dial: an egress probe
    can report a non-routable address in sandboxed/NATed environments."""
    import socket

    candidates = []
    if loopback:
        candidates.append("127.0.0.1")
    try:
        probe = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        probe.connect(("8.8.8.8", 80))  # no packets sent; just picks the egress iface
        candidates.append(probe.getsockname()[0])
        probe.close()
    except OSError:
        pass
    try:
        candidates.append(socket.gethostbyname(socket.gethostname()))
    except OSError:
        pass
    candidates.append("127.0.0.1")
    for host in candidates:
        try:
            srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            srv.bind((host, 0))
            srv.listen(1)
            port = srv.getsockname()[1]
            dial = socket.create_connection((host, port), timeout=1.0)
            dial.close()
            srv.close()
            return host, port
        except OSError:
            try:
                srv.close()
            except OSError:
                pass
    raise RuntimeError("no dialable interface for the jax.distributed coordinator")


def _init_jax_distributed(coordinator: str, num_processes: int):
    # import jax only inside workers — the driver must stay off the TPU
    # (reference warning: jax_trainer.py:88-89)
    import jax

    from ray_tpu.train import context as _ctx

    rank = _ctx.get_context().get_world_rank()
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=rank,
    )


def _shutdown_jax_distributed():
    import jax

    try:
        jax.distributed.shutdown()
    except Exception:
        pass


# ----------------------------------------------------------------------
# Torch backend (CPU parity; reference: train/torch/config.py)
# ----------------------------------------------------------------------
@dataclass
class TorchConfig(BackendConfig):
    backend: str = "gloo"
    init_method: str = "tcp"
    timeout_s: int = 1800

    @property
    def backend_cls(self):
        return _TorchBackend


class _TorchBackend(Backend):
    def on_start(self, worker_group, backend_config: "TorchConfig"):
        n = len(worker_group)
        if n <= 1:
            return
        host, port = worker_group.execute_single(0, _free_coordinator_addr)
        worker_group.execute(
            _init_torch_process_group, f"tcp://{host}:{port}", n, backend_config.backend
        )

    def on_shutdown(self, worker_group, backend_config):
        try:
            worker_group.execute(_destroy_torch_process_group)
        except Exception:
            pass


def _init_torch_process_group(init_method: str, world_size: int, backend: str):
    import torch.distributed as dist

    from ray_tpu.train import context as _ctx

    rank = _ctx.get_context().get_world_rank()
    dist.init_process_group(backend=backend, init_method=init_method, world_size=world_size, rank=rank)


def _destroy_torch_process_group():
    import torch.distributed as dist

    if dist.is_initialized():
        dist.destroy_process_group()
