"""ray_tpu.train: distributed training on the actor runtime.

TPU-native Train library (reference: python/ray/train + train/v2): a
controller/worker-group topology where each worker is an actor on one host
of a TPU slice, `jax.distributed` is bootstrapped across workers, and the
user's step function runs under pjit/GSPMD so DP/FSDP/TP/SP are sharding
configs, not wrapper modules (reference equivalents:
train/v2/api/data_parallel_trainer.py, train/v2/jax/jax_trainer.py:19).
"""

from ray_tpu.util.usage import record_library_usage as _rlu

_rlu("train")

from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    RunConfig,
    ScalingConfig,
)
from ray_tpu.train.context import TrainContext, get_context
from ray_tpu.train.result import Result
from ray_tpu.train.session import get_checkpoint, get_dataset_shard, report
from ray_tpu.train.scaling_policy import (
    ElasticScalingPolicy,
    FixedScalingPolicy,
    NoopDecision,
    ResizeDecision,
    ScalingPolicy,
)
from ray_tpu.train.trainer import DataParallelTrainer, JaxTrainer, TorchTrainer
from ray_tpu.train.gbdt import GBDTTrainer, HistGBDT, LightGBMTrainer, XGBoostTrainer
from ray_tpu.train.errors import TrainingFailedError
from ray_tpu.train import torch_utils as torch  # train.torch.prepare_model (reference API shape)

import sys as _sys

# make `import ray_tpu.train.torch` / `from ray_tpu.train.torch import
# prepare_model` work too (the import style reference users port with)
_sys.modules[__name__ + ".torch"] = torch

__all__ = [
    "Checkpoint",
    "CheckpointConfig",
    "DataParallelTrainer",
    "FailureConfig",
    "GBDTTrainer",
    "HistGBDT",
    "JaxTrainer",
    "LightGBMTrainer",
    "XGBoostTrainer",
    "Result",
    "RunConfig",
    "ScalingConfig",
    "TorchTrainer",
    "TrainContext",
    "TrainingFailedError",
    "get_checkpoint",
    "get_context",
    "get_dataset_shard",
    "report",
]
