"""Torch train-loop utilities: prepare_model / prepare_data_loader.

Reference parity: python/ray/train/torch/train_loop_utils.py
(ray.train.torch.prepare_model :1 wraps DDP with the right device and
process group; prepare_data_loader adds a DistributedSampler and device
movement). TPU-native note: JAX loops need none of this — sharding is
declarative (parallel/train_step.py) — so these utilities exist for
CPU/torch parity workloads running under TorchConfig (gloo).

Usage inside a DataParallelTrainer(train_loop, backend=TorchConfig()):

    def train_loop(config):
        model = train.torch.prepare_model(Net())
        loader = train.torch.prepare_data_loader(loader)
        for batch in loader: ...
"""

from __future__ import annotations

from ray_tpu.train.backend import TorchConfig  # noqa: F401  (train.torch.TorchConfig)


def get_device():
    """The device this worker should place tensors on (CPU in this image;
    the seam matches the reference so accelerator builds slot in)."""
    import torch

    return torch.device("cpu")


def prepare_model(model, *, ddp_kwargs: dict | None = None):
    """Move the model to the worker's device and wrap it in
    DistributedDataParallel when world_size > 1 (no-op wrap for 1 worker,
    like the reference). Requires the process group TorchConfig.on_start
    initialized."""
    import torch
    import torch.distributed as dist

    from ray_tpu.train.context import get_context

    model = model.to(get_device())
    if get_context().get_world_size() <= 1:
        return model
    if not dist.is_initialized():
        raise RuntimeError(
            "torch.distributed is not initialized; run under "
            "DataParallelTrainer(..., backend=TorchConfig()) so the gloo "
            "process group exists before prepare_model"
        )
    return torch.nn.parallel.DistributedDataParallel(model, **(ddp_kwargs or {}))


def prepare_data_loader(data_loader, *, add_dist_sampler: bool = True):
    """Shard a DataLoader across the group with a DistributedSampler
    (reference: prepare_data_loader). Non-default samplers are preserved
    when add_dist_sampler=False."""
    import torch
    from torch.utils.data import DataLoader, DistributedSampler

    from ray_tpu.train.context import get_context

    ctx = get_context()
    if ctx.get_world_size() <= 1 or not add_dist_sampler:
        return data_loader
    if data_loader.batch_size is None:
        # a custom batch_sampler owns batching AND sampling; replacing it
        # with a DistributedSampler would silently un-batch the stream
        raise ValueError(
            "prepare_data_loader cannot re-shard a DataLoader built with a "
            "custom batch_sampler; shard inside your batch_sampler and pass "
            "add_dist_sampler=False"
        )
    sampler = getattr(data_loader, "sampler", None)
    if sampler is not None and not isinstance(
        sampler, (torch.utils.data.SequentialSampler, torch.utils.data.RandomSampler)
    ):
        raise ValueError(
            f"prepare_data_loader would replace your custom sampler "
            f"({type(sampler).__name__}); pass add_dist_sampler=False to keep it"
        )
    dist_sampler = DistributedSampler(
        data_loader.dataset,
        num_replicas=ctx.get_world_size(),
        rank=ctx.get_world_rank(),
        shuffle=isinstance(sampler, torch.utils.data.RandomSampler),
    )
    kwargs = dict(
        batch_size=data_loader.batch_size,
        sampler=dist_sampler,
        num_workers=data_loader.num_workers,
        collate_fn=data_loader.collate_fn,
        pin_memory=data_loader.pin_memory,
        drop_last=data_loader.drop_last,
        timeout=data_loader.timeout,
        worker_init_fn=data_loader.worker_init_fn,
        generator=data_loader.generator,
    )
    if data_loader.num_workers > 0:  # only valid with workers
        kwargs["persistent_workers"] = data_loader.persistent_workers
        kwargs["prefetch_factor"] = data_loader.prefetch_factor
    return DataLoader(data_loader.dataset, **kwargs)


def backward(loss):
    """Reference-API compatibility (train.torch.backward): plain backward
    (no AMP scaler on CPU)."""
    loss.backward()
