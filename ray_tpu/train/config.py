"""Train configuration dataclasses.

Reference parity: python/ray/air/config.py (ScalingConfig/RunConfig/
FailureConfig/CheckpointConfig) + train/v2/api/config.py:70-104
(ScalingConfig.use_tpu/topology/accelerator_type for TPU slices).
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field


@dataclass
class ScalingConfig:
    """How many workers and what each one holds.

    TPU path: ``use_tpu=True`` + ``topology`` ("2x2", "4x4", ...) gang-
    reserves one slice via SlicePlacementGroup and places one worker per
    host; ``num_workers`` is then derived from the slice host count.
    """

    num_workers: int = 1
    use_tpu: bool = False
    use_gpu: bool = False  # accepted for API parity; TPU build ignores it
    topology: str | None = None
    accelerator_version: str = "v5e"
    accelerator_type: str | None = None
    resources_per_worker: dict | None = None
    placement_strategy: str = "PACK"

    def __post_init__(self):
        if self.accelerator_type and not self.use_tpu:
            self.use_tpu = self.accelerator_type.upper().startswith("TPU")

    @property
    def _worker_resources(self) -> dict:
        res = dict(self.resources_per_worker or {})
        res.setdefault("CPU", 1.0)
        return res


@dataclass
class FailureConfig:
    """Reference: air/config.py FailureConfig; train v2 failure_handling/.

    max_failures: total worker-group restarts allowed (-1 = infinite).
    """

    max_failures: int = 0


@dataclass
class CheckpointConfig:
    """Reference: air/config.py CheckpointConfig (top-k retention)."""

    num_to_keep: int | None = None
    checkpoint_score_attribute: str | None = None
    checkpoint_score_order: str = "max"

    def __post_init__(self):
        if self.checkpoint_score_order not in ("max", "min"):
            raise ValueError("checkpoint_score_order must be 'max' or 'min'")


@dataclass
class RunConfig:
    """Reference: air/config.py RunConfig."""

    name: str | None = None
    storage_path: str | None = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    verbose: int = 1
    # experiment-lifecycle hooks (ray_tpu.tune.callbacks; reference:
    # air RunConfig.callbacks)
    callbacks: list = field(default_factory=list)

    def __post_init__(self):
        if self.storage_path is None:
            self.storage_path = os.environ.get(
                "RT_STORAGE_PATH", os.path.expanduser("~/ray_tpu_results")
            )
        if self.name is None:
            import time

            self.name = f"train-{time.strftime('%Y%m%d-%H%M%S')}-{os.getpid()}"
