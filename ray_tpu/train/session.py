"""Worker-side session API: report / get_checkpoint / get_dataset_shard.

Reference parity: ray.train.report (train/_internal/session.py), the only
Train call on the hot path — per-step overhead must be ~0 (SURVEY.md §3.4
hot-loop note): report() enqueues to the worker actor's outbox and returns;
persistence happens on the controller.
"""

from __future__ import annotations

from ray_tpu.train import context as _ctx
from ray_tpu.train._checkpoint import Checkpoint


def report(metrics: dict, checkpoint: Checkpoint | None = None, checkpoint_dir_name: str | None = None):
    """Report metrics (+ optionally a checkpoint) from every worker.

    Synchronization contract (reference: train v2 report_handler): all
    workers must call report() the same number of times; the controller
    consumes one "round" when every rank has reported.
    """
    ctx = _ctx.get_context()
    if ctx is None:
        # local/debug mode: no-op sink so loops run outside a Trainer
        return
    if ctx._report_fn is not None:
        with ctx._lock:
            ctx._report_seq += 1
            seq = ctx._report_seq
        ctx._report_fn(seq, dict(metrics), checkpoint, checkpoint_dir_name)


def get_checkpoint() -> Checkpoint | None:
    """Latest committed checkpoint (set on restore/restart)."""
    ctx = _ctx.get_context()
    return ctx._latest_checkpoint if ctx is not None else None


def get_dataset_shard(dataset_name: str = "train"):
    ctx = _ctx.get_context()
    if ctx is None:
        return None
    return ctx._dataset_shards.get(dataset_name)
