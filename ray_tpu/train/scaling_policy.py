"""Elastic training: worker-group sizing decisions.

Reference parity: train/v2/_internal/execution/scaling_policy/
scaling_policy.py:29 — the ScalingPolicy decision API (NoopDecision /
ResizeDecision) consulted when a worker group is (re)created and while it
runs. TPU-native semantics: a resize is a RESTART BOUNDARY — the jitted
SPMD program is compiled for a fixed mesh, so growing or shrinking the
group means recompiling against the new topology and resuming from the
latest committed checkpoint (orbax shards re-load under the new
sharding). The controller therefore applies resize decisions by tearing
the group down exactly like a failure restart, minus the failure count.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class NoopDecision:
    reason: str = ""


@dataclass
class ResizeDecision:
    num_workers: int
    reason: str = ""


class ScalingPolicy:
    """Decision hooks (reference: scaling_policy.py:29).

    - workers_for_attempt(): group size for the NEXT worker-group start.
    - poll_running(): consulted periodically while a group trains; a
      ResizeDecision triggers a checkpoint-resume restart at the new size.
    """

    def __init__(self, scaling_config):
        self.scaling_config = scaling_config

    def workers_for_attempt(self) -> int:
        return self.scaling_config.num_workers

    def poll_running(self, group_size: int):
        return NoopDecision()


class FixedScalingPolicy(ScalingPolicy):
    """Always the configured size (the reference default)."""


class ElasticScalingPolicy(ScalingPolicy):
    """Fit the group to cluster capacity within [min_workers, max_workers].

    Sizing uses AVAILABLE capacity, never the cluster total: co-tenant
    jobs hold resources too, and a resize targeting capacity someone else
    owns would tear down a working group for a placement that can never
    succeed. At attempt start the previous group has already released its
    bundles, so available reflects what this job can actually reserve.
    While running, upscale when the AVAILABLE headroom fits extra workers
    (a node joined / a tenant left); downscale only when the cluster
    TOTAL can no longer hold the current group (a node died — the failure
    path usually fires first). poll_interval_s throttles the checks."""

    def __init__(self, scaling_config, min_workers: int = 1, max_workers: int | None = None, poll_interval_s: float = 1.0):
        super().__init__(scaling_config)
        self.min_workers = max(1, int(min_workers))
        self.max_workers = int(max_workers) if max_workers else max(scaling_config.num_workers, self.min_workers)
        self.poll_interval_s = poll_interval_s
        self._last_poll = 0.0

    def _fit(self, resources: dict) -> int:
        res = self.scaling_config._worker_resources
        fit = None
        for k, per in res.items():
            if per > 0:
                fit_k = int(resources.get(k, 0) // per)
                fit = fit_k if fit is None else min(fit, fit_k)
        return self.max_workers if fit is None else fit

    def _clamp(self, n: int) -> int:
        return max(self.min_workers, min(self.max_workers, n))

    def workers_for_attempt(self) -> int:
        import ray_tpu

        return self._clamp(self._fit(ray_tpu.available_resources()))

    def poll_running(self, group_size: int):
        import time

        import ray_tpu

        now = time.monotonic()
        if now - self._last_poll < self.poll_interval_s:
            return NoopDecision()
        self._last_poll = now
        headroom = self._fit(ray_tpu.available_resources())
        target = self._clamp(group_size + headroom)
        if target > group_size:
            return ResizeDecision(target, reason=f"headroom for {target - group_size} more workers")
        total_fit = self._clamp(self._fit(ray_tpu.cluster_resources()))
        if total_fit < group_size:
            return ResizeDecision(total_fit, reason=f"cluster now fits only {total_fit} workers")
        return NoopDecision()
