"""Per-worker training context.

Reference parity: python/ray/train/context.py (get_context() giving
world_size/rank/local_rank) + train/v2 TrainContext. The context lives in a
thread-local-free module global inside each worker process; the controller
seeds it before the user loop starts.
"""

from __future__ import annotations

import threading


class TrainContext:
    def __init__(
        self,
        world_size: int,
        world_rank: int,
        local_rank: int,
        local_world_size: int,
        node_rank: int,
        experiment_name: str,
        trial_name: str | None = None,
        trial_id: str | None = None,
        report_fn=None,
        latest_checkpoint=None,
        dataset_shards: dict | None = None,
        attempt_uid: str = "0",
    ):
        self._world_size = world_size
        self._world_rank = world_rank
        self._local_rank = local_rank
        self._local_world_size = local_world_size
        self._node_rank = node_rank
        self._experiment_name = experiment_name
        self._trial_name = trial_name
        self._trial_id = trial_id
        self._report_fn = report_fn
        self._latest_checkpoint = latest_checkpoint
        self._dataset_shards = dataset_shards or {}
        self._attempt_uid = attempt_uid  # unique per worker-group attempt
        self._report_seq = 0
        self._lock = threading.Lock()

    def get_world_size(self) -> int:
        return self._world_size

    def get_world_rank(self) -> int:
        return self._world_rank

    def get_local_rank(self) -> int:
        return self._local_rank

    def get_local_world_size(self) -> int:
        return self._local_world_size

    def get_node_rank(self) -> int:
        return self._node_rank

    def get_experiment_name(self) -> str:
        return self._experiment_name

    def get_trial_name(self):
        return self._trial_name

    def get_trial_id(self):
        return self._trial_id


_context: TrainContext | None = None


def get_context() -> TrainContext | None:
    return _context


def set_context(ctx: TrainContext | None):
    global _context
    _context = ctx
