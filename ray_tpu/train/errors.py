"""Train error types (reference: train/v2/api/exceptions.py)."""

from __future__ import annotations


class TrainingFailedError(RuntimeError):
    """Raised by Trainer.fit() when training fails beyond the failure
    policy's patience. `.training_error` holds the worker exception."""

    def __init__(self, msg: str, training_error: BaseException | None = None):
        super().__init__(msg)
        self.training_error = training_error


class WorkerGroupError(RuntimeError):
    """One or more workers in the group failed; maps worker rank -> error."""

    def __init__(self, msg: str, worker_failures: dict):
        super().__init__(f"{msg}: {worker_failures}")
        self.worker_failures = worker_failures
