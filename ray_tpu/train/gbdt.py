"""Distributed gradient-boosted decision trees (GBDT) training.

Reference parity: python/ray/train/gbdt_trainer.py + train/xgboost/
xgboost_trainer.py + train/lightgbm/lightgbm_trainer.py — data-parallel
boosting where every worker holds a dataset shard and per-node gradient
histograms are allreduced so all workers grow IDENTICAL trees (the
`tree_method=hist` + rabit-allreduce algorithm xgboost runs under the
reference's trainer).

This image has neither xgboost nor lightgbm wheels, so the engine here is
a native numpy implementation of the same histogram algorithm — second-
order boosting (gradient + hessian), quantile-free uniform binning over
allreduced per-feature ranges, depth-wise growth with the xgboost gain
formula. ``XGBoostTrainer`` / ``LightGBMTrainer`` are API-compatible
shims that map the familiar param names onto it; plug the real libraries
in by overriding ``GBDTTrainer._make_train_loop`` when wheels exist.

The histogram sync rides ``ray_tpu.train.collective.allreduce`` (the
same worker-group collective the reference's rabit tracker fills), so
determinism across workers comes from identical global histograms, not
from sharing trees.
"""

from __future__ import annotations

import numpy as np

from ray_tpu.train.trainer import DataParallelTrainer


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-np.clip(x, -30, 30)))


class _Node:
    __slots__ = ("feature", "threshold_bin", "left", "right", "leaf_value")

    def __init__(self, leaf_value=None, feature=None, threshold_bin=None, left=None, right=None):
        self.leaf_value = leaf_value
        self.feature = feature
        self.threshold_bin = threshold_bin
        self.left = left
        self.right = right


class HistGBDT:
    """Histogram GBDT with a pluggable allreduce seam.

    ``histogram_reduce(arr) -> arr`` sums a float64 array across workers;
    the default (identity) trains single-process. All split decisions are
    taken on REDUCED histograms, so every worker with the same bin edges
    grows the same trees.
    """

    def __init__(
        self,
        n_estimators: int = 50,
        max_depth: int = 4,
        learning_rate: float = 0.3,
        n_bins: int = 64,
        objective: str = "reg:squarederror",
        reg_lambda: float = 1.0,
        min_child_weight: float = 1e-3,
        min_gain: float = 0.0,
    ):
        assert objective in ("reg:squarederror", "binary:logistic"), objective
        self.n_estimators = n_estimators
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.n_bins = n_bins
        self.objective = objective
        self.reg_lambda = reg_lambda
        self.min_child_weight = min_child_weight
        self.min_gain = min_gain
        self.trees: list[_Node] = []
        self.bin_edges: np.ndarray | None = None  # [F, n_bins-1]
        self.base_score = 0.0

    # -- binning -------------------------------------------------------
    def _bin(self, X):
        """Map features to uint8 bin ids using self.bin_edges."""
        B = np.empty(X.shape, np.int32)
        for f in range(X.shape[1]):
            B[:, f] = np.searchsorted(self.bin_edges[f], X[:, f], side="right")
        return B

    # -- training ------------------------------------------------------
    def fit(self, X, y, histogram_reduce=None, extrema_reduce=None, eval_every: int = 0, eval_cb=None):
        """Fit on the local shard (X [N,F], y [N]).

        histogram_reduce: SUM across workers (float64 array -> array).
        extrema_reduce: elementwise MAX across workers; defaults to
        identity. Both default to single-process."""
        X = np.asarray(X, np.float64)
        y = np.asarray(y, np.float64)
        reduce_sum = histogram_reduce or (lambda a: a)
        reduce_max = extrema_reduce or (lambda a: a)
        N, F = X.shape

        # global feature ranges -> shared uniform bin edges
        lo = X.min(axis=0) if N else np.full(F, np.inf)
        hi = X.max(axis=0) if N else np.full(F, -np.inf)
        ext = reduce_max(np.concatenate([-lo, hi]))
        glo, ghi = -ext[:F], ext[F:]
        span = np.where(ghi > glo, ghi - glo, 1.0)
        # n_bins-1 interior edges -> bin ids in [0, n_bins-1]
        steps = np.arange(1, self.n_bins, dtype=np.float64) / self.n_bins
        self.bin_edges = glo[:, None] + span[:, None] * steps[None, :]

        # base score: global mean (sum trick over [sum_y, count])
        agg = reduce_sum(np.array([y.sum(), float(N)]))
        mean = agg[0] / max(agg[1], 1.0)
        if self.objective == "binary:logistic":
            p = np.clip(mean, 1e-6, 1 - 1e-6)
            self.base_score = float(np.log(p / (1 - p)))
        else:
            self.base_score = float(mean)

        B = self._bin(X)
        pred = np.full(N, self.base_score)
        for _ in range(self.n_estimators):
            if self.objective == "binary:logistic":
                prob = _sigmoid(pred)
                g, h = prob - y, np.maximum(prob * (1 - prob), 1e-12)
            else:
                g, h = pred - y, np.ones(N)
            tree = self._grow_tree(B, g, h, reduce_sum)
            self.trees.append(tree)
            pred += self._predict_binned(tree, B)
            if eval_cb is not None and eval_every and len(self.trees) % eval_every == 0:
                eval_cb(len(self.trees), self._metrics(pred, y, reduce_sum))
        return self._metrics(pred, y, reduce_sum)

    def _metrics(self, pred, y, reduce_sum) -> dict:
        if self.objective == "binary:logistic":
            p = np.clip(_sigmoid(pred), 1e-12, 1 - 1e-12)
            ll = -(y * np.log(p) + (1 - y) * np.log(1 - p))
            err = (p > 0.5).astype(np.float64) != y
            agg = reduce_sum(np.array([ll.sum(), err.sum(), float(len(y))]))
            n = max(agg[2], 1.0)
            return {"logloss": agg[0] / n, "error": agg[1] / n}
        se = (pred - y) ** 2
        agg = reduce_sum(np.array([se.sum(), float(len(y))]))
        return {"rmse": float(np.sqrt(agg[0] / max(agg[1], 1.0)))}

    def _grow_tree(self, B, g, h, reduce_sum) -> _Node:
        root_rows = np.arange(len(g))
        gh = reduce_sum(np.array([g.sum(), h.sum()]))
        return self._split_node(B, g, h, root_rows, gh[0], gh[1], 0, reduce_sum)

    def _split_node(self, B, g, h, rows, G, H, depth, reduce_sum) -> _Node:
        lam = self.reg_lambda
        leaf = _Node(leaf_value=float(-G / (H + lam) * self.learning_rate))
        if depth >= self.max_depth or H < 2 * self.min_child_weight:
            return leaf

        # per-(feature, bin) gradient histogram on local rows, then SUM
        # across workers — the one communication per node (xgboost hist)
        F = B.shape[1]
        nb = self.n_bins
        hist = np.zeros((2, F, nb), np.float64)
        if len(rows):
            sub = B[rows]
            gr, hr = g[rows], h[rows]
            for f in range(F):
                hist[0, f] = np.bincount(sub[:, f], weights=gr, minlength=nb)[:nb]
                hist[1, f] = np.bincount(sub[:, f], weights=hr, minlength=nb)[:nb]
        hist = reduce_sum(hist.ravel()).reshape(2, F, nb)

        GL = np.cumsum(hist[0], axis=1)[:, :-1]  # left sums per split point
        HL = np.cumsum(hist[1], axis=1)[:, :-1]
        GR, HR = G - GL, H - HL
        parent = G * G / (H + lam)
        gain = GL * GL / (HL + lam) + GR * GR / (HR + lam) - parent
        gain = np.where((HL >= self.min_child_weight) & (HR >= self.min_child_weight), gain, -np.inf)
        f_best, b_best = np.unravel_index(int(np.argmax(gain)), gain.shape)
        if not np.isfinite(gain[f_best, b_best]) or gain[f_best, b_best] <= self.min_gain:
            return leaf

        mask = B[rows, f_best] <= b_best
        left_rows, right_rows = rows[mask], rows[~mask]
        node = _Node(feature=int(f_best), threshold_bin=int(b_best))
        node.left = self._split_node(B, g, h, left_rows, GL[f_best, b_best], HL[f_best, b_best], depth + 1, reduce_sum)
        node.right = self._split_node(B, g, h, right_rows, GR[f_best, b_best], HR[f_best, b_best], depth + 1, reduce_sum)
        return node

    # -- inference -----------------------------------------------------
    def _predict_binned(self, tree: _Node, B) -> np.ndarray:
        # vectorized level-order walk: rows carry their current node
        out = np.empty(len(B))
        stack = [(tree, np.arange(len(B)))]
        while stack:
            node, idx = stack.pop()
            if node.feature is None:
                out[idx] = node.leaf_value
                continue
            mask = B[idx, node.feature] <= node.threshold_bin
            stack.append((node.left, idx[mask]))
            stack.append((node.right, idx[~mask]))
        return out

    def predict(self, X) -> np.ndarray:
        """Raw margin (regression value / logit)."""
        X = np.asarray(X, np.float64)
        B = self._bin(X)
        pred = np.full(len(X), self.base_score)
        for t in self.trees:
            pred += self._predict_binned(t, B)
        return pred

    def predict_proba(self, X) -> np.ndarray:
        assert self.objective == "binary:logistic"
        return _sigmoid(self.predict(X))


def _shard_to_numpy(shard, label_column: str):
    rows_X, rows_y = [], []
    for batch in shard.iter_batches(batch_size=4096):
        y = np.asarray(batch[label_column], np.float64)
        feats = [np.asarray(batch[k], np.float64).reshape(len(y), -1) for k in sorted(batch) if k != label_column]
        rows_X.append(np.concatenate(feats, axis=1))
        rows_y.append(y)
    if not rows_X:
        return np.zeros((0, 1)), np.zeros(0)
    return np.concatenate(rows_X), np.concatenate(rows_y)


def _make_gbdt_loop(label_column: str, params: dict, num_boost_round: int):
    def loop(config):
        import pickle
        import tempfile

        from ray_tpu import train
        from ray_tpu.train import collective as tcol
        from ray_tpu.train import session

        ctx = train.get_context()
        shard = session.get_dataset_shard("train")
        X, y = _shard_to_numpy(shard, label_column)

        multi = ctx.get_world_size() > 1
        reduce_sum = tcol.allreduce if multi else None
        # emulate elementwise MAX over SUM-only collectives: allgather
        # would also do, but max(stack) via repeated pairwise sum is
        # wrong — use the collective's own max op if present, else
        # allgather. ray_tpu.collective.allreduce supports MAX.
        extrema = None
        if multi:
            import ray_tpu.collective as col

            from ray_tpu.train.collective import _ensure_group

            extrema = lambda a: col.allreduce(a, group_name=_ensure_group(), op=col.ReduceOp.MAX)  # noqa: E731
            # agree on the GLOBAL feature width first: a rank whose shard
            # got zero blocks (block count < world size) has X of shape
            # (0, 1) and would feed wrong-shaped buffers into every
            # subsequent reduce, wedging the whole group
            f_global = int(extrema(np.array([float(X.shape[1] if len(X) else 0)]))[0])
            if len(X) == 0:
                X = np.zeros((0, max(f_global, 1)))
            elif X.shape[1] != f_global:
                raise ValueError(
                    f"rank {ctx.get_world_rank()}: shard has {X.shape[1]} feature "
                    f"columns but the group agreed on {f_global}"
                )

        model = HistGBDT(n_estimators=num_boost_round, **params)
        final = model.fit(X, y, histogram_reduce=reduce_sum, extrema_reduce=extrema)
        if ctx.get_world_rank() == 0:
            d = tempfile.mkdtemp()
            with open(f"{d}/model.pkl", "wb") as f:
                pickle.dump(model, f)
            from ray_tpu.train import Checkpoint

            session.report({"trees": len(model.trees), **final}, checkpoint=Checkpoint.from_directory(d))
        else:
            session.report({"trees": len(model.trees), **final})

    return loop


class GBDTTrainer(DataParallelTrainer):
    """Data-parallel GBDT over dataset shards (reference:
    train/gbdt_trainer.py). Workers sync per-node gradient histograms via
    the train collective and grow identical trees."""

    def __init__(
        self,
        *,
        datasets: dict,
        label_column: str,
        params: dict | None = None,
        num_boost_round: int = 20,
        scaling_config=None,
        run_config=None,
        **kw,
    ):
        params = dict(params or {})
        super().__init__(
            _make_gbdt_loop(label_column, params, num_boost_round),
            scaling_config=scaling_config,
            run_config=run_config,
            datasets=datasets,
            **kw,
        )

    @staticmethod
    def get_model(checkpoint) -> HistGBDT:
        """Load the fitted model back from a Result checkpoint."""
        import os
        import pickle

        with open(os.path.join(checkpoint.path, "model.pkl"), "rb") as f:
            return pickle.load(f)


_XGB_PARAM_MAP = {
    "eta": "learning_rate",
    "learning_rate": "learning_rate",
    "max_depth": "max_depth",
    "lambda": "reg_lambda",
    "reg_lambda": "reg_lambda",
    "objective": "objective",
    "min_child_weight": "min_child_weight",
    "max_bin": "n_bins",
}

_LGBM_PARAM_MAP = {
    **_XGB_PARAM_MAP,
    "num_leaves": None,  # depth-wise growth here; accepted and ignored
    "lambda_l2": "reg_lambda",
}


def _map_params(params: dict, table: dict, trainer: str) -> dict:
    out = {}
    for k, v in (params or {}).items():
        if k not in table:
            raise ValueError(f"{trainer}: unsupported param {k!r} (supported: {sorted(table)})")
        tgt = table[k]
        if tgt is not None:
            out[tgt] = v
    if out.get("objective") not in (None, "reg:squarederror", "binary:logistic"):
        raise ValueError(f"{trainer}: objective {out['objective']!r} not supported by the native engine")
    return out


class XGBoostTrainer(GBDTTrainer):
    """xgboost-flavored param surface over the native histogram engine
    (reference: train/xgboost/xgboost_trainer.py — there it wraps
    xgboost+rabit; this image has no xgboost wheel, and the hist+allreduce
    algorithm is the same)."""

    def __init__(self, *, params: dict | None = None, num_boost_round: int = 20, **kw):
        super().__init__(params=_map_params(params, _XGB_PARAM_MAP, "XGBoostTrainer"), num_boost_round=num_boost_round, **kw)


class LightGBMTrainer(GBDTTrainer):
    """lightgbm-flavored param surface over the native histogram engine
    (reference: train/lightgbm/lightgbm_trainer.py)."""

    def __init__(self, *, params: dict | None = None, num_boost_round: int = 20, **kw):
        super().__init__(params=_map_params(params, _LGBM_PARAM_MAP, "LightGBMTrainer"), num_boost_round=num_boost_round, **kw)
