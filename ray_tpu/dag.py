"""DAG API: lazily-bound task/actor graphs.

Reference parity: python/ray/dag/ (DAGNode, FunctionNode, ClassNode,
ClassMethodNode; compiled execution in compiled_dag_node.py). This module
provides the lazy .bind()/.execute() graph; compiled-graph channel execution
for accelerator pipelines lives in ray_tpu.parallel.pipeline (the TPU-native
equivalent of NCCL-channel compiled graphs).
"""

from __future__ import annotations

from ray_tpu.core.object_ref import ObjectRef


class DAGNode:
    def __init__(self, args: tuple, kwargs: dict):
        self._bound_args = args
        self._bound_kwargs = kwargs

    def _resolve(self, v, memo: dict):
        if isinstance(v, DAGNode):
            return v._execute_memo(memo)
        if isinstance(v, (list, tuple)):
            return type(v)(self._resolve(x, memo) for x in v)
        if isinstance(v, dict):
            return {k: self._resolve(x, memo) for k, x in v.items()}
        return v

    def _resolved_args(self, memo: dict):
        args = tuple(self._resolve(a, memo) for a in self._bound_args)
        kwargs = {k: self._resolve(v, memo) for k, v in self._bound_kwargs.items()}
        return args, kwargs

    def _execute_memo(self, memo: dict):
        if id(self) not in memo:
            memo[id(self)] = self._execute_impl(memo)
        return memo[id(self)]

    def execute(self, *input_args):
        """Run the DAG; InputNode placeholders are filled positionally."""
        memo = {"__inputs__": input_args}
        return self._execute_memo(memo)

    def _execute_impl(self, memo):  # pragma: no cover - abstract
        raise NotImplementedError


class InputNode(DAGNode):
    """Placeholder for execute()-time arguments (reference:
    python/ray/dag/input_node.py)."""

    _counter = 0

    def __init__(self, index: int | None = None):
        super().__init__((), {})
        if index is None:
            index = InputNode._counter
            InputNode._counter += 1
        self.index = index

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        InputNode._counter = 0
        return False

    def _execute_impl(self, memo):
        return memo["__inputs__"][self.index]


class FunctionNode(DAGNode):
    def __init__(self, remote_fn, args, kwargs):
        super().__init__(args, kwargs)
        self._remote_fn = remote_fn

    def _execute_impl(self, memo) -> ObjectRef:
        args, kwargs = self._resolved_args(memo)
        return self._remote_fn.remote(*args, **kwargs)


class ClassNode(DAGNode):
    def __init__(self, actor_cls, args, kwargs):
        super().__init__(args, kwargs)
        self._actor_cls = actor_cls
        self._handle = None

    def _execute_impl(self, memo):
        if self._handle is None:
            args, kwargs = self._resolved_args(memo)
            self._handle = self._actor_cls.remote(*args, **kwargs)
        return self._handle

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return _ClassMethodBinder(self, name)


class _ClassMethodBinder:
    def __init__(self, class_node: ClassNode, method: str):
        self._class_node = class_node
        self._method = method

    def bind(self, *args, **kwargs):
        return ClassMethodNode(self._class_node, self._method, args, kwargs)


class ClassMethodNode(DAGNode):
    def __init__(self, class_node: ClassNode, method: str, args, kwargs):
        super().__init__(args, kwargs)
        self._class_node = class_node
        self._method = method

    def _execute_impl(self, memo) -> ObjectRef:
        handle = self._class_node._execute_memo(memo)
        args, kwargs = self._resolved_args(memo)
        return getattr(handle, self._method).remote(*args, **kwargs)


class ActorMethodNode(DAGNode):
    """bind() on an already-created actor handle's method."""

    def __init__(self, handle, method: str, args, kwargs):
        super().__init__(args, kwargs)
        self._handle = handle
        self._method = method

    def _execute_impl(self, memo) -> ObjectRef:
        args, kwargs = self._resolved_args(memo)
        return getattr(self._handle, self._method).remote(*args, **kwargs)


MultiOutputNode = list  # reference API alias: wraps several leaf nodes
