"""DAG API: lazily-bound task/actor graphs.

Reference parity: python/ray/dag/ (DAGNode, FunctionNode, ClassNode,
ClassMethodNode; compiled execution in compiled_dag_node.py). This module
provides the lazy .bind()/.execute() graph; compiled-graph channel execution
for accelerator pipelines lives in ray_tpu.parallel.pipeline (the TPU-native
equivalent of NCCL-channel compiled graphs).

Data-plane note: every DAG edge passes the upstream ObjectRef STRAIGHT
into the downstream task's args (no driver-side get), so edge bytes move
store-to-store — same-host via zero-copy shm attach, cross-host via the
chunked transfer service (core/transport.py) — while the head carries
only the submit control messages. execute() returns leaf ObjectRefs
without waiting, so successive invocations pipeline naturally.
"""

from __future__ import annotations

from ray_tpu.core.object_ref import ObjectRef


class DAGNode:
    def __init__(self, args: tuple, kwargs: dict):
        self._bound_args = args
        self._bound_kwargs = kwargs

    def _resolve(self, v, memo: dict):
        if isinstance(v, DAGNode):
            return v._execute_memo(memo)
        if isinstance(v, (list, tuple)):
            return type(v)(self._resolve(x, memo) for x in v)
        if isinstance(v, dict):
            return {k: self._resolve(x, memo) for k, x in v.items()}
        return v

    def _resolved_args(self, memo: dict):
        args = tuple(self._resolve(a, memo) for a in self._bound_args)
        kwargs = {k: self._resolve(v, memo) for k, v in self._bound_kwargs.items()}
        return args, kwargs

    def _execute_memo(self, memo: dict):
        if id(self) not in memo:
            memo[id(self)] = self._execute_impl(memo)
        return memo[id(self)]

    def execute(self, *input_args):
        """Run the DAG; InputNode placeholders are filled positionally."""
        memo = {"__inputs__": input_args}
        return self._execute_memo(memo)

    def experimental_compile(self, *, channels: bool = False, nslots: int = 8, buffer_size_bytes: int = 256 << 10):
        """Compile this DAG for repeated execution (reference:
        compiled_dag_node.py). Topology is validated and actors are
        instantiated ONCE at compile time; each execute() then walks a
        flat pre-ordered schedule.

        ``channels=True`` compiles to persistent shm-ring channels with
        per-actor execution loops — the head leaves the steady-state path
        entirely and each hop is a ~30us doorbell
        (ray_tpu.experimental.compiled_dag; same-host actor-method DAGs).
        (Accelerator-tensor pipelines — the reference's NCCL-channel use
        of compiled graphs — are the GSPMD microbatch pipeline in
        ray_tpu.parallel.pipeline, which compiles the whole schedule into
        one XLA program.)"""
        if channels:
            from ray_tpu.experimental.compiled_dag import ChannelCompiledDAG

            return ChannelCompiledDAG(self, nslots=nslots, buffer_size_bytes=buffer_size_bytes)
        return CompiledDAG(self)

    def _execute_impl(self, memo):  # pragma: no cover - abstract
        raise NotImplementedError


class InputNode(DAGNode):
    """Placeholder for execute()-time arguments (reference:
    python/ray/dag/input_node.py)."""

    _counter = 0

    def __init__(self, index: int | None = None):
        super().__init__((), {})
        if index is None:
            index = InputNode._counter
            InputNode._counter += 1
        self.index = index

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        InputNode._counter = 0
        return False

    def _execute_impl(self, memo):
        return memo["__inputs__"][self.index]


class FunctionNode(DAGNode):
    def __init__(self, remote_fn, args, kwargs):
        super().__init__(args, kwargs)
        self._remote_fn = remote_fn

    def _execute_impl(self, memo) -> ObjectRef:
        args, kwargs = self._resolved_args(memo)
        return self._remote_fn.remote(*args, **kwargs)


class ClassNode(DAGNode):
    def __init__(self, actor_cls, args, kwargs):
        super().__init__(args, kwargs)
        self._actor_cls = actor_cls
        self._handle = None

    def _execute_impl(self, memo):
        if self._handle is None:
            args, kwargs = self._resolved_args(memo)
            self._handle = self._actor_cls.remote(*args, **kwargs)
        return self._handle

    def __getattr__(self, name):
        if name.startswith("_"):
            raise AttributeError(name)
        return _ClassMethodBinder(self, name)


class _ClassMethodBinder:
    def __init__(self, class_node: ClassNode, method: str):
        self._class_node = class_node
        self._method = method

    def bind(self, *args, **kwargs):
        return ClassMethodNode(self._class_node, self._method, args, kwargs)


class ClassMethodNode(DAGNode):
    def __init__(self, class_node: ClassNode, method: str, args, kwargs):
        super().__init__(args, kwargs)
        self._class_node = class_node
        self._method = method

    def _execute_impl(self, memo) -> ObjectRef:
        handle = self._class_node._execute_memo(memo)
        args, kwargs = self._resolved_args(memo)
        return getattr(handle, self._method).remote(*args, **kwargs)


class ActorMethodNode(DAGNode):
    """bind() on an already-created actor handle's method."""

    def __init__(self, handle, method: str, args, kwargs):
        super().__init__(args, kwargs)
        self._handle = handle
        self._method = method

    def _execute_impl(self, memo) -> ObjectRef:
        args, kwargs = self._resolved_args(memo)
        return getattr(self._handle, self._method).remote(*args, **kwargs)


class CompiledDAG:
    """Compiled execution over a validated DAG topology.

    Compile time: walk the graph once, detect cycles, record a
    dependency-ordered schedule, and instantiate every ClassNode's actor
    (so replays reuse warm actors instead of re-creating them — the
    driver-side analogue of the reference's one-time channel setup in
    compiled_dag_node.py). Execute time: fill InputNodes positionally and
    submit every node along the schedule in one pass; returns the leaf's
    ObjectRef (or a list of them for MultiOutputNode leaves)."""

    def __init__(self, leaf):
        self._leaves = list(leaf) if isinstance(leaf, list) else [leaf]
        self._schedule: list[DAGNode] = []
        seen: dict[int, int] = {}  # id -> 0 visiting, 1 done
        input_indices: set[int] = set()

        def visit(node):
            if not isinstance(node, DAGNode):
                return
            st = seen.get(id(node))
            if st == 1:
                return
            if st == 0:
                raise ValueError("cycle detected in DAG")
            seen[id(node)] = 0
            deps = list(node._bound_args) + list(node._bound_kwargs.values())
            if isinstance(node, (ClassMethodNode,)):
                deps.append(node._class_node)
            for d in deps:
                if isinstance(d, (list, tuple)):
                    for x in d:
                        visit(x)
                elif isinstance(d, dict):
                    for x in d.values():
                        visit(x)
                else:
                    visit(d)
            if isinstance(node, InputNode):
                input_indices.add(node.index)
            seen[id(node)] = 1
            self._schedule.append(node)

        for lf in self._leaves:
            visit(lf)
        self.num_inputs = (max(input_indices) + 1) if input_indices else 0
        # hoist actor creation: ClassNodes with static (non-DAG) args are
        # instantiated now; their handles persist across execute() calls
        boot_memo: dict = {"__inputs__": ()}
        for node in self._schedule:
            if isinstance(node, ClassNode) and not any(
                isinstance(a, DAGNode) for a in list(node._bound_args) + list(node._bound_kwargs.values())
            ):
                node._execute_memo(boot_memo)

    def execute(self, *input_args):
        if len(input_args) < self.num_inputs:
            raise ValueError(f"compiled DAG takes {self.num_inputs} inputs, got {len(input_args)}")
        memo = {"__inputs__": input_args}
        for node in self._schedule:
            node._execute_memo(memo)
        outs = [memo[id(lf)] for lf in self._leaves]
        return outs if len(outs) > 1 else outs[0]

    def teardown(self):
        """Kill compile-time actors (reference: CompiledDAG.teardown)."""
        import ray_tpu

        for node in self._schedule:
            if isinstance(node, ClassNode) and node._handle is not None:
                try:
                    ray_tpu.kill(node._handle)
                except Exception:
                    pass
                node._handle = None


MultiOutputNode = list  # reference API alias: wraps several leaf nodes


def compile_dag(leaf_or_leaves) -> CompiledDAG:
    """Compile a DAG leaf (or MultiOutputNode list of leaves)."""
    return CompiledDAG(leaf_or_leaves)
