"""ActorPool: load-balance tasks over a fixed set of actors.

Reference parity: python/ray/util/actor_pool.py — same API (map /
map_unordered / submit / get_next / get_next_unordered / has_next /
has_free / push / pop_idle). Submits beyond the actor count queue and
dispatch as actors free up (on task completion).
"""

from __future__ import annotations

import ray_tpu


class ActorPool:
    def __init__(self, actors):
        self._idle = list(actors)
        # future -> (index, actor_or_None); actor becomes None once it has
        # been returned to the idle pool (its task finished)
        self._future_to_actor: dict = {}
        self._index_to_future: dict = {}
        self._pending_submits: list = []  # (fn, value) waiting for an actor
        self._next_task_index = 0
        self._next_return_index = 0

    # ---- submission ----
    def submit(self, fn, value):
        """fn(actor, value) -> ObjectRef. With no free actor the submit is
        queued and dispatched when one frees."""
        if not self._idle:
            self._pending_submits.append((fn, value))
            return
        actor = self._idle.pop(0)
        ref = fn(actor, value)
        self._future_to_actor[ref] = (self._next_task_index, actor)
        self._index_to_future[self._next_task_index] = ref
        self._next_task_index += 1

    def map(self, fn, values):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn, values):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    # ---- internals ----
    def _return_actor(self, actor):
        self._idle.append(actor)
        if self._pending_submits:
            self.submit(*self._pending_submits.pop(0))

    def _release_future(self, ref):
        """Mark ref's actor free (its task completed); keep the result."""
        idx, actor = self._future_to_actor[ref]
        if actor is not None:
            self._future_to_actor[ref] = (idx, None)
            self._return_actor(actor)

    def _wait_any(self, timeout):
        live = list(self._future_to_actor)
        ready, _ = ray_tpu.wait(live, num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("no result within timeout")
        self._release_future(ready[0])

    # ---- consumption ----
    def has_next(self) -> bool:
        return bool(self._future_to_actor) or bool(self._pending_submits)

    def has_free(self) -> bool:
        return bool(self._idle) and not self._pending_submits

    def get_next(self, timeout: float | None = None):
        """Next result in submission order."""
        while self._next_return_index not in self._index_to_future:
            if self._pending_submits and self._idle:
                self.submit(*self._pending_submits.pop(0))
                continue
            if not self._future_to_actor:
                raise StopIteration("no pending results")
            self._wait_any(timeout)
        ref = self._index_to_future.pop(self._next_return_index)
        self._next_return_index += 1
        try:
            return ray_tpu.get(ref, timeout=timeout)
        finally:
            self._release_future(ref)
            del self._future_to_actor[ref]

    def get_next_unordered(self, timeout: float | None = None):
        """Whichever pending result lands first."""
        if not self._future_to_actor and self._pending_submits and self._idle:
            self.submit(*self._pending_submits.pop(0))
        if not self._future_to_actor:
            raise StopIteration("no pending results")
        ready, _ = ray_tpu.wait(list(self._future_to_actor), num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("no result within timeout")
        ref = ready[0]
        try:
            return ray_tpu.get(ref)
        finally:
            self._release_future(ref)
            idx, _ = self._future_to_actor.pop(ref)
            self._index_to_future.pop(idx, None)

    # ---- membership ----
    def push(self, actor):
        self._return_actor(actor)

    def pop_idle(self):
        return self._idle.pop() if self._idle else None
