"""Metrics API: Counter / Gauge / Histogram with cluster aggregation.

Reference parity: python/ray/util/metrics.py (Counter/Gauge/Histogram with
tag_keys, default tags, .inc/.set/.observe) + the dashboard's Prometheus
export. Collapsed transport: every process accumulates locally; worker
processes flush their registry into the head's GCS KV (namespace
"_metrics") on a background thread, and `get_metrics_snapshot()` /
`export_prometheus()` merge all processes' series.

    from ray_tpu.util import metrics
    c = metrics.Counter("requests_total", description="...", tag_keys=("route",))
    c.inc(1.0, tags={"route": "/api"})
"""

from __future__ import annotations

import bisect
import os
import threading
import time

_DEFAULT_HIST_BOUNDARIES = [0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10]


class _Registry:
    def __init__(self):
        self.lock = threading.Lock()
        self.metrics: dict[str, "Metric"] = {}
        self._flusher: threading.Thread | None = None

    def register(self, m: "Metric"):
        with self.lock:
            existing = self.metrics.get(m.name)
            if existing is not None:
                if existing.kind != m.kind or getattr(existing, "boundaries", None) != getattr(m, "boundaries", None):
                    raise ValueError(
                        f"metric {m.name!r} already registered as {existing.kind}"
                        f"{' with different boundaries' if existing.kind == m.kind else ''}"
                    )
                return existing
            self.metrics[m.name] = m
            self._ensure_flusher()
            return m

    def snapshot(self) -> dict:
        with self.lock:
            return {name: m._dump() for name, m in self.metrics.items()}

    def _ensure_flusher(self):
        # only worker processes push; the driver is read locally
        if self._flusher is not None or os.environ.get("RT_WORKER_ID") is None:
            return
        self._flusher = threading.Thread(target=self._flush_loop, daemon=True, name="rt-metrics-flush")
        self._flusher.start()

    def flush_once(self):
        """Push this process's registry into the head's GCS KV, stamped
        with a wall-clock timestamp so the merge side can expire gauges
        from dead workers (counters/histograms still fold in — they are
        lifetime totals, valid forever)."""
        from ray_tpu.core import context

        wid = os.environ.get("RT_WORKER_ID", str(os.getpid()))
        try:
            client = context.get_client()
            client.kv(
                "put",
                key=f"proc::{wid}",
                value={"ts": time.time(), "metrics": self.snapshot()},
                namespace="_metrics",
            )
        except Exception:
            pass

    def _flush_loop(self):
        while True:
            time.sleep(1.0)
            self.flush_once()


_registry = _Registry()


class _BoundSeries:
    """Pre-resolved (metric, series-key) handle for hot paths — the
    reference prometheus-client's ``.labels(...)`` pattern. Skips the
    per-call tag merge/validation of inc/set/observe; the caller promises
    the values it passes are sane (e.g. no negative counter incs). Used
    by the serving telemetry plane, whose per-step budget is tens of
    microseconds (llm/telemetry.py)."""

    __slots__ = ("_metric", "_key")

    def __init__(self, metric: "Metric", key: tuple):
        self._metric = metric
        self._key = key

    def inc(self, value: float = 1.0):
        m = self._metric
        with m._lock:
            m._series[self._key] = float(m._series.get(self._key, 0.0)) + value

    def set(self, value: float):
        m = self._metric
        with m._lock:
            m._series[self._key] = float(value)

    def observe(self, value: float):
        m = self._metric
        with m._lock:
            buckets = m._series.get(self._key)
            if not isinstance(buckets, list):
                buckets = [0.0, 0.0] + [0.0] * (len(m.boundaries) + 1)
                m._series[self._key] = buckets
            buckets[0] += 1
            buckets[1] += value
            buckets[2 + bisect.bisect_left(m.boundaries, value)] += 1


class Metric:
    kind = "untyped"

    def __init__(self, name: str, description: str = "", tag_keys: tuple = ()):
        if not name or not name.replace("_", "a").isalnum():
            raise ValueError(f"invalid metric name {name!r}")
        self.name = name
        self.description = description
        self.tag_keys = tuple(tag_keys)
        self._default_tags: dict = {}
        self._series: dict[tuple, float | list] = {}
        self._lock = threading.Lock()
        shared = _registry.register(self)
        if shared is not self:
            # same name registered twice in one process: share the series
            self._series = shared._series
            self._lock = shared._lock

    def set_default_tags(self, tags: dict):
        self._default_tags = dict(tags)
        return self

    def bind(self, tags: dict | None = None) -> _BoundSeries:
        """Resolve ``tags`` once and return a hot-path handle whose
        inc/set/observe skip the per-call merge/validation."""
        return _BoundSeries(self, self._key(tags))

    def _key(self, tags: dict | None) -> tuple:
        merged = {**self._default_tags, **(tags or {})}
        extra = set(merged) - set(self.tag_keys)
        if extra:
            raise ValueError(f"tags {extra} not in tag_keys {self.tag_keys}")
        return tuple(str(merged.get(k, "")) for k in self.tag_keys)

    def _dump(self) -> dict:
        with self._lock:
            return {
                "kind": self.kind,
                "description": self.description,
                "tag_keys": self.tag_keys,
                "series": {",".join(k): v if not isinstance(v, list) else list(v) for k, v in self._series.items()},
            }


class Counter(Metric):
    kind = "counter"

    def inc(self, value: float = 1.0, tags: dict | None = None):
        if value < 0:
            raise ValueError("counters only increase")
        k = self._key(tags)
        with self._lock:
            self._series[k] = float(self._series.get(k, 0.0)) + value


class Gauge(Metric):
    kind = "gauge"

    def set(self, value: float, tags: dict | None = None):
        with self._lock:
            self._series[self._key(tags)] = float(value)


class Histogram(Metric):
    kind = "histogram"

    def __init__(self, name, description: str = "", boundaries=None, tag_keys: tuple = ()):
        self.boundaries = list(boundaries or _DEFAULT_HIST_BOUNDARIES)
        super().__init__(name, description, tag_keys)

    def observe(self, value: float, tags: dict | None = None):
        k = self._key(tags)
        with self._lock:
            buckets = self._series.get(k)
            if not isinstance(buckets, list):
                # [count, sum, bucket_counts...]
                buckets = [0.0, 0.0] + [0.0] * (len(self.boundaries) + 1)
                self._series[k] = buckets
            buckets[0] += 1
            buckets[1] += value
            buckets[2 + bisect.bisect_left(self.boundaries, value)] += 1

    def _dump(self) -> dict:
        d = super()._dump()
        d["boundaries"] = self.boundaries
        return d


# ----------------------------------------------------------------------
# aggregation / export (driver side)
# ----------------------------------------------------------------------
# A worker's flushed snapshot outlives the worker in the GCS KV: without
# an expiry, a dead replica's last gauge values (queue depth, occupancy)
# freeze into the merged view forever. Snapshots older than this window
# drop their GAUGE series; counters/histograms are lifetime totals and
# keep folding in (workers re-flush every 1s, so live ones never expire).
STALE_SNAPSHOT_S = float(os.environ.get("RT_METRICS_STALE_S", "15"))


def get_metrics_snapshot(client=None) -> dict:
    """Merged view: local registry + every worker's flushed registry.
    Worker snapshots carry a flush timestamp; ones older than
    ``STALE_SNAPSHOT_S`` contribute counters/histograms only (gauges
    expire with their writer)."""
    from ray_tpu.core import context

    merged: dict = {}

    def fold(proc_snap: dict, stale: bool = False):
        for name, m in proc_snap.items():
            agg = merged.setdefault(
                name,
                {"kind": m["kind"], "description": m["description"], "tag_keys": tuple(m["tag_keys"]), "series": {}},
            )
            if "boundaries" in m:
                agg["boundaries"] = m["boundaries"]
            if m["kind"] == "gauge" and stale:
                continue  # dead writer: its point-in-time values expired
            for key, val in m["series"].items():
                cur = agg["series"].get(key)
                if isinstance(val, list):
                    agg["series"][key] = [a + b for a, b in zip(cur, val)] if cur else list(val)
                elif m["kind"] == "gauge":
                    agg["series"][key] = val  # last writer wins
                else:
                    agg["series"][key] = (cur or 0.0) + val

    fold(_registry.snapshot())
    try:
        c = client or context.get_client()
        for key in c.kv("keys", prefix="proc::", namespace="_metrics"):
            snap = c.kv("get", key=key, namespace="_metrics")
            if not snap:
                continue
            stale = False
            if isinstance(snap, dict) and "metrics" in snap and "ts" in snap:
                stale = (time.time() - float(snap["ts"])) > STALE_SNAPSHOT_S
                snap = snap["metrics"]
            fold(snap, stale=stale)
    except Exception:
        pass
    return merged


_core_counter_last: dict = {}
_core_counter_lock = threading.Lock()  # concurrent scrapes must not double-inc


def update_core_metrics(client) -> None:
    """Refresh the core runtime series (rt_tasks_*, rt_object_store_*,
    rt_transfer_*) from live cluster state — called on every /metrics
    scrape so the Grafana panels (dashboard/grafana.py) are backed by
    real data (reference: the autogenerated ray_* core metrics)."""
    try:
        states = client.cluster_info("tasks")
        counts: dict[str, int] = {}
        for t in states:
            counts[t["status"]] = counts.get(t["status"], 0) + 1
        Gauge("rt_tasks_running", description="tasks currently executing").set(float(counts.get("RUNNING", 0)))
        Gauge("rt_tasks_pending", description="tasks queued or waiting").set(
            float(counts.get("PENDING", 0) + counts.get("QUEUED", 0) + counts.get("WAITING", 0))
        )
        # lifetime totals, NOT windowed states() counts: record pruning
        # would freeze a counter derived from the window
        life = client.task_manager.lifetime_counts()
        _bump_counter("rt_tasks_finished_total", "tasks finished", float(life["finished"]))
        _bump_counter("rt_tasks_submitted_total", "tasks submitted", float(life["submitted"]))
        obj = client.cluster_info("objects")
        Gauge("rt_object_store_bytes", description="sealed shm bytes").set(float(obj.get("shm_bytes", 0)))
        Gauge("rt_object_store_spilled_bytes", description="spilled bytes").set(float(obj.get("spilled_bytes", 0)))
        from ray_tpu.core import transport

        _bump_counter("rt_transfer_pull_bytes_total", "bytes pulled", float(transport.STATS.get("pull_bytes", 0)))
        _bump_counter("rt_transfer_serve_bytes_total", "bytes served", float(transport.STATS.get("serve_bytes", 0)))
    except Exception:
        pass


def _bump_counter(name: str, desc: str, absolute: float) -> None:
    """Drive a Counter from an absolute external total (inc by delta)."""
    c = Counter(name, description=desc)  # registers the series even at 0
    c.inc(0.0)
    with _core_counter_lock:
        last = _core_counter_last.get(name, 0.0)
        delta = absolute - last
        _core_counter_last[name] = max(last, absolute)
    if delta > 0:
        c.inc(delta)


def _escape_label(v: str) -> str:
    """Prometheus label-value escaping: backslash, double-quote, newline
    (exposition format spec). Without it a tag like model="a\"b" corrupts
    the whole scrape."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(v: str) -> str:
    """HELP-text escaping: backslash and newline only (quotes are legal)."""
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def export_prometheus(client=None) -> str:
    """Prometheus text exposition of the merged snapshot."""
    if client is not None:
        update_core_metrics(client)
    lines = []
    for name, m in sorted(get_metrics_snapshot(client).items()):
        lines.append(f"# HELP {name} {_escape_help(m['description'])}")
        lines.append(f"# TYPE {name} {m['kind']}")
        for key, val in m["series"].items():
            tags = ""
            if m["tag_keys"]:
                vals = key.split(",")
                tags = "{" + ",".join(f'{k}="{_escape_label(v)}"' for k, v in zip(m["tag_keys"], vals)) + "}"
            if isinstance(val, list):
                count, total, *buckets = val
                bounds = m.get("boundaries", _DEFAULT_HIST_BOUNDARIES)
                cum = 0.0
                for b, n in zip(list(bounds) + ["+Inf"], buckets):
                    cum += n
                    lb = tags[:-1] + "," if tags else "{"
                    lines.append(f'{name}_bucket{lb}le="{b}"}} {cum:g}')
                lines.append(f"{name}_count{tags} {count:g}")
                lines.append(f"{name}_sum{tags} {total:g}")
            else:
                lines.append(f"{name}{tags} {val:g}")
    return "\n".join(lines) + "\n"
