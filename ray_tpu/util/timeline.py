"""Task timeline export (chrome://tracing format).

Reference parity: ray.timeline() backed by
src/ray/core_worker/task_event_buffer.h task events — here the
TaskManager's per-task (state, timestamp) event lists are rendered into
trace-event JSON: one complete ("X") event per RUNNING->terminal span,
rows (tid) = workers, process groups (pid) = nodes. Open the file in
chrome://tracing or Perfetto.
"""

from __future__ import annotations

import json


def timeline(filename: str | None = None, client=None) -> list[dict]:
    from ray_tpu.core import context

    c = client or context.get_client()
    events: list[dict] = []
    tm = c.task_manager
    with tm._lock:
        tasks = list(tm._tasks.values())
    for st in tasks:
        run_start = None
        for state, ts in st.events:
            if state == "RUNNING":
                run_start = ts
            elif state in ("FINISHED", "FAILED", "CANCELLED") and run_start is not None:
                events.append(
                    {
                        "name": st.spec.name,
                        "ph": "X",
                        "ts": run_start * 1e6,
                        "dur": max(0.0, (ts - run_start)) * 1e6,
                        "pid": st.node_id.hex()[:8] if st.node_id else "head",
                        "tid": st.worker_id.hex()[:8] if st.worker_id else "?",
                        "cat": "actor_task" if st.spec.actor_id is not None else "task",
                        "args": {"status": state, "attempts": st.attempts_done},
                    }
                )
                run_start = None
    if filename:
        with open(filename, "w") as f:
            json.dump(events, f)
    return events
