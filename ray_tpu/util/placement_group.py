"""Placement group public API.

Reference parity: python/ray/util/placement_group.py — placement_group()
(:146), PlacementGroup.ready() (:61), strategies PACK/SPREAD/STRICT_PACK/
STRICT_SPREAD; backed by atomic bundle reservation (reference: 2-phase
commit in gcs/gcs_placement_group_scheduler.h).
"""

from __future__ import annotations

from ray_tpu.core.context import get_client
from ray_tpu.core.ids import ObjectID, PlacementGroupID
from ray_tpu.core.object_ref import ObjectRef

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")


def _pg_ready_oid(pg_id: PlacementGroupID) -> ObjectID:
    return ObjectID(pg_id.binary() + b"\xfd\xfd\xfd\xfd")


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: list[dict] | None = None):
        self.id = pg_id
        self._bundles = bundles

    def ready(self) -> ObjectRef:
        """ObjectRef sealed (True) once every bundle is reserved."""
        return ObjectRef(_pg_ready_oid(self.id))

    def wait(self, timeout_seconds: float | None = None) -> bool:
        return get_client().pg("wait", pg_id=self.id, timeout=timeout_seconds)

    @property
    def bundle_specs(self) -> list[dict]:
        if self._bundles is None:
            for row in get_client().pg("table"):
                if row["pg_id"] == self.id.hex():
                    self._bundles = row["bundles"]
                    break
        return self._bundles or []

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def __eq__(self, other):
        return isinstance(other, PlacementGroup) and self.id == other.id

    def __hash__(self):
        return hash(self.id)

    def __reduce__(self):
        return (PlacementGroup, (self.id, self._bundles))


def placement_group(
    bundles: list[dict],
    strategy: str = "PACK",
    name: str = "",
    lifetime: str | None = None,
) -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"invalid strategy {strategy!r}; one of {VALID_STRATEGIES}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty resource dicts")
    pg_id = get_client().pg("create", bundles=bundles, strategy=strategy, name=name)
    return PlacementGroup(pg_id, bundles)


def remove_placement_group(pg: PlacementGroup):
    get_client().pg("remove", pg_id=pg.id)


def placement_group_table() -> list[dict]:
    return get_client().pg("table")


def get_current_placement_group() -> PlacementGroup | None:
    return None  # capture-child-tasks semantics not yet wired
