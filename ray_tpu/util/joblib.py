"""joblib backend over ray_tpu (reference: ray/util/joblib/__init__.py —
register_ray() so sklearn's n_jobs parallelism fans out to the cluster).

    from ray_tpu.util.joblib import register_ray
    register_ray()
    with joblib.parallel_backend("ray_tpu"):
        ...
"""

from __future__ import annotations


def register_ray():
    from joblib import register_parallel_backend
    from joblib._parallel_backends import MultiprocessingBackend

    import ray_tpu

    class RayTpuBackend(MultiprocessingBackend):
        supports_timeout = True

        def configure(self, n_jobs=1, parallel=None, prefer=None, require=None, **kw):
            n_jobs = self.effective_n_jobs(n_jobs)
            self.parallel = parallel
            from ray_tpu.util.multiprocessing import Pool

            self._pool = Pool(processes=n_jobs)
            return n_jobs

        def effective_n_jobs(self, n_jobs):
            if n_jobs == 0:
                raise ValueError("n_jobs == 0 has no meaning")
            if not ray_tpu.is_initialized():
                ray_tpu.init()
            cpus = int(ray_tpu.cluster_resources().get("CPU", 1))
            return cpus if n_jobs is None or n_jobs < 0 else n_jobs

        def terminate(self):
            if getattr(self, "_pool", None) is not None:
                self._pool.terminate()

    register_parallel_backend("ray_tpu", RayTpuBackend)
