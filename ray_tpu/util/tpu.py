"""TPU slice gang scheduling.

Reference parity: python/ray/util/tpu.py — SlicePlacementGroup (:52),
slice_placement_group (:227), reserve_tpu_slice (tpu accelerator module
:213-264): reserve the slice head via a label-selected placement group, then
build a full-slice PG (one bundle per host, SPREAD across the slice's
hosts) so a worker group lands on every host of one slice atomically.
"""

from __future__ import annotations

import logging

from ray_tpu.accelerators.tpu import chips_per_host, num_hosts, pod_type_chip_count
from ray_tpu.core.context import get_client
from ray_tpu.util.placement_group import PlacementGroup, placement_group, remove_placement_group

logger = logging.getLogger(__name__)


def reserve_tpu_slice(topology: str, accelerator_version: str, timeout_s: float = 600.0) -> str | None:
    """Reserve one whole slice by claiming its head resource; returns the
    slice name (the per-slice resource key) or None."""
    pod_type = _pod_type(topology, accelerator_version)
    head_resource = f"TPU-{pod_type}-head"
    pg = placement_group([{head_resource: 1}], strategy="STRICT_PACK", name=f"slice-head-{pod_type}")
    if not pg.wait(timeout_seconds=timeout_s):
        remove_placement_group(pg)
        return None
    # find which slice we landed on via the chosen node's labels
    client = get_client()
    table = {row["pg_id"]: row for row in client.pg("table")}
    row = table.get(pg.id.hex())
    slice_name = None
    if row and row["nodes"]:
        for n in client.cluster_info("nodes"):
            if n["node_id"] == row["nodes"][0]:
                slice_name = n["labels"].get("ray_tpu.io/tpu-slice-name")
                break
    # head PG's job is done once we know the slice; the slice PG pins hosts
    if slice_name is None:
        remove_placement_group(pg)
        return None
    _head_pgs[slice_name] = pg
    return slice_name


_head_pgs: dict = {}


def _pod_type(topology: str, accelerator_version: str) -> str:
    ver = accelerator_version.lower()
    gen = {"v5e": "v5litepod", "v5litepod": "v5litepod"}.get(ver, ver)
    chips = 1
    for p in topology.lower().split("x"):
        chips *= int(p)
    from ray_tpu.accelerators.tpu import GENERATION_CORES_PER_CHIP

    cores = chips * GENERATION_CORES_PER_CHIP.get(gen, 1)
    return f"{gen}-{cores}"


class SlicePlacementGroup:
    """Gang reservation of a full TPU slice: one bundle per host carrying
    that host's chips + the slice-name resource (reference: util/tpu.py:52)."""

    def __init__(
        self,
        topology: str,
        accelerator_version: str = "v5e",
        chips_per_host_override: int | None = None,
        timeout_s: float = 600.0,
    ):
        self.topology = topology
        self.accelerator_version = accelerator_version
        self.pod_type = _pod_type(topology, accelerator_version)
        self._chips_per_host = chips_per_host_override or chips_per_host(self.pod_type, topology)
        self._num_hosts = max(pod_type_chip_count(self.pod_type) // self._chips_per_host, 1)
        self.slice_name = reserve_tpu_slice(topology, accelerator_version, timeout_s=timeout_s)
        if self.slice_name is None:
            raise TimeoutError(f"could not reserve a {self.pod_type} slice (head resource unavailable)")
        bundles = [
            {"TPU": float(self._chips_per_host), self.slice_name: 1.0}
            for _ in range(self._num_hosts)
        ]
        self._pg = placement_group(bundles, strategy="STRICT_SPREAD", name=f"slice-{self.slice_name}")

    @property
    def placement_group(self) -> PlacementGroup:
        return self._pg

    @property
    def num_hosts(self) -> int:
        return self._num_hosts

    @property
    def chips_per_host(self) -> int:
        return self._chips_per_host

    @property
    def num_chips(self) -> int:
        return self._num_hosts * self._chips_per_host

    def wait(self, timeout_seconds: float | None = None) -> bool:
        return self._pg.wait(timeout_seconds=timeout_seconds)

    def remove(self):
        remove_placement_group(self._pg)
        head = _head_pgs.pop(self.slice_name, None)
        if head is not None:
            remove_placement_group(head)


def slice_placement_group(topology: str, accelerator_version: str = "v5e", **kw) -> SlicePlacementGroup:
    return SlicePlacementGroup(topology, accelerator_version, **kw)


def simulate_tpu_slice_nodes(client, pod_type: str, slice_name: str, num_cpus_per_host: int = 8):
    """Test/dev helper: register simulated nodes shaped like one TPU slice
    (the in-process analogue of the reference's fake multi-node cluster +
    GKE env detection)."""
    cph = chips_per_host(pod_type)
    hosts = num_hosts(pod_type)
    nodes = []
    for wid in range(hosts):
        resources = {"CPU": float(num_cpus_per_host), "TPU": float(cph), slice_name: 1.0}
        if wid == 0:
            resources[f"TPU-{pod_type}-head"] = 1.0
        labels = {
            "ray_tpu.io/tpu-slice-name": slice_name,
            "ray_tpu.io/tpu-worker-id": str(wid),
            "ray_tpu.io/tpu-pod-type": pod_type,
        }
        nodes.append(client.add_node(resources, labels=labels))
    return nodes
