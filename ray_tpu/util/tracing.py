"""Distributed tracing: spans at remote-call boundaries.

Reference parity: the OpenTelemetry integration in
python/ray/util/tracing/tracing_helper.py — every task/actor submission
opens a client span, the executing worker opens a server span whose
parent is the caller's, and trace context propagates through NESTED
remote calls, so one trace id stitches a whole call tree across
processes. Here spans are written as JSONL (one file per process under
the session dir) in an OTel-compatible shape — no collector dependency;
`load_spans()` merges them for tools/tests and the dashboard.

Enable with RT_TRACING=1 (or tracing.configure(True)). Disabled, the
hooks are a single boolean check.
"""

from __future__ import annotations

import json
import os
import threading
import time
import uuid

_enabled: bool | None = None
# contextvars, not threading.local: asyncio tasks each carry their own
# context, so concurrent coroutines on one actor event loop keep distinct
# trace contexts (threads get isolated contexts too)
import contextvars

_current: contextvars.ContextVar = contextvars.ContextVar("rt_trace_ctx", default=None)
_file_lock = threading.Lock()
_file = None


def configure(enabled: bool):
    global _enabled
    _enabled = bool(enabled)


def enabled() -> bool:
    global _enabled
    if _enabled is None:
        _enabled = os.environ.get("RT_TRACING", "0").lower() in ("1", "true", "on")
    return _enabled


def _ctx() -> tuple | None:
    return _current.get()


def set_context(ctx: tuple | None):
    """(trace_id, span_id) of the CURRENT span in this thread/task."""
    _current.set(ctx)


def child_context() -> tuple:
    """Context to attach to an outgoing remote call: same trace (new if
    none), caller's span as parent."""
    cur = _ctx()
    if cur is None:
        return (uuid.uuid4().hex[:16], None)
    return cur


def _span_file():
    global _file
    with _file_lock:
        if _file is None:
            import atexit

            from ray_tpu.util.state import session_dir

            d = os.path.join(session_dir(), "spans")
            os.makedirs(d, exist_ok=True)
            _file = open(os.path.join(d, f"spans-{os.getpid()}.jsonl"), "a", buffering=1)
            # flush-close at interpreter exit: a process's final spans
            # (e.g. the decode replica's finish span) must reach disk
            # even when nobody calls shutdown() explicitly
            atexit.register(shutdown)
        return _file


def shutdown():
    """Flush and close this process's span file. Idempotent; recording a
    span afterwards transparently reopens the same per-pid file (append
    mode), so late stragglers are kept rather than crashing. Called from
    atexit and from the worker exit path (core/worker_main.py) so a
    worker's final spans are never lost to a dangling file handle."""
    global _file
    with _file_lock:
        f, _file = _file, None
    if f is not None:
        try:
            f.flush()
            f.close()
        except (OSError, ValueError):
            pass


def record_span(name: str, kind: str, trace_id: str, span_id: str, parent_id, start_ns: int, end_ns: int, attrs: dict):
    try:
        _span_file().write(
            json.dumps(
                {
                    "name": name,
                    "kind": kind,
                    "trace_id": trace_id,
                    "span_id": span_id,
                    "parent_id": parent_id,
                    "start_ns": start_ns,
                    "end_ns": end_ns,
                    "attrs": attrs,
                }
            )
            + "\n"
        )
    except Exception:
        pass


class span:
    """Context manager: open a span under `parent_ctx` (or the thread's
    current context), make it current inside the block."""

    def __init__(self, name: str, kind: str = "internal", parent_ctx: tuple | None = None, **attrs):
        self.name = name
        self.kind = kind
        self.parent_ctx = parent_ctx
        self.attrs = attrs

    def __enter__(self):
        ctx = self.parent_ctx if self.parent_ctx is not None else child_context()
        self.trace_id = ctx[0]
        self.parent_id = ctx[1]
        self.span_id = uuid.uuid4().hex[:16]
        self._saved = _ctx()
        set_context((self.trace_id, self.span_id))
        self.start_ns = time.time_ns()
        return self

    def __exit__(self, exc_type, exc, tb):
        set_context(self._saved)
        if exc_type is not None:
            self.attrs["error"] = repr(exc)
        record_span(
            self.name, self.kind, self.trace_id, self.span_id, self.parent_id, self.start_ns, time.time_ns(), self.attrs
        )
        return False


def load_spans(pid: int | None = None) -> list[dict]:
    """Merge every process's span file for the session (driver + workers
    share the session dir via RT_SESSION_PID)."""
    from ray_tpu.util.state import session_dir

    d = os.path.join(session_dir(pid), "spans")
    out: list[dict] = []
    try:
        names = os.listdir(d)
    except OSError:
        return out
    for n in sorted(names):
        try:
            with open(os.path.join(d, n)) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        out.append(json.loads(line))
        except (OSError, ValueError):
            continue
    return out
