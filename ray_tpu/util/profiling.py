"""Profiling helpers: jax.profiler capture + wall-time probes.

Reference parity gap (SURVEY §5.1): the reference ships py-spy/torch
profiler plumbing; the TPU-native equivalents are XLA's profiler traces
(TensorBoard-viewable) captured around jitted regions.

    with profile_trace("/tmp/tb"):        # XLA device trace
        step(state, batch)

    prof = WallProfiler(); ...; prof.report()
"""

from __future__ import annotations

import contextlib
import time


@contextlib.contextmanager
def profile_trace(logdir: str, host_tracer_level: int = 2):
    """jax.profiler.trace wrapper; view with tensorboard --logdir."""
    import jax

    jax.profiler.start_trace(logdir)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()


def start_profiler_server(port: int = 9999):
    """On-demand capture endpoint (tensorboard 'capture profile')."""
    import jax

    jax.profiler.start_server(port)
    return port


class WallProfiler:
    """Named wall-time spans with device sync, for quick perf triage."""

    def __init__(self):
        self.spans: dict[str, list[float]] = {}

    @contextlib.contextmanager
    def span(self, name: str, sync_value=None):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if sync_value is not None:
                import jax

                jax.block_until_ready(sync_value)
            self.spans.setdefault(name, []).append(time.perf_counter() - t0)

    def report(self) -> dict:
        return {
            name: {"count": len(v), "total_s": sum(v), "mean_s": sum(v) / len(v)}
            for name, v in self.spans.items()
            if v
        }
