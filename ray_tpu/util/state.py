"""State API: programmatic cluster introspection + session state dumps.

Reference parity: python/ray/util/state (list_tasks/list_actors/
list_nodes/list_objects/list_placement_groups, summarize_*) backed by the
head's live registries instead of a state-API server. For out-of-process
inspection (the CLI), the head periodically dumps a JSON snapshot under
the session dir (/tmp/ray_tpu/session_<pid>/state.json) — scripts/cli.py
reads the freshest session.
"""

from __future__ import annotations

import collections
import json
import os
import time


def _client():
    from ray_tpu.core import context

    return context.get_client()


def list_nodes() -> list[dict]:
    return _client().cluster_info("nodes")


def list_actors() -> list[dict]:
    return _client().cluster_info("actors")


def list_tasks() -> list[dict]:
    return _client().cluster_info("tasks")


def list_objects() -> dict:
    return _client().cluster_info("objects")


def list_placement_groups() -> list[dict]:
    return _client().cluster_info("placement_groups")


def summarize_tasks() -> dict:
    """Counts by (name, state) — reference: `ray summary tasks`."""
    by_state: dict = collections.defaultdict(lambda: collections.defaultdict(int))
    for t in list_tasks():
        by_state[t.get("name", "?")][t.get("state", "?")] += 1
    return {name: dict(states) for name, states in by_state.items()}


def summarize_actors() -> dict:
    by_state: dict = collections.defaultdict(int)
    for a in list_actors():
        by_state[a.get("state", "?")] += 1
    return dict(by_state)


def cluster_status(client=None) -> dict:
    """`ray status`-shaped summary."""
    c = client or _client()
    actors = collections.defaultdict(int)
    for a in c.cluster_info("actors"):
        actors[a.get("state", "?")] += 1
    return {
        "nodes": c.cluster_info("nodes"),
        "cluster_resources": c.cluster_info("cluster_resources"),
        "available_resources": c.cluster_info("available_resources"),
        "pending_demand": c.scheduler.pending_demand() if hasattr(c, "scheduler") else [],
        "actors": dict(actors),
        # lifetime totals (never pruned) — throughput must derive from
        # these, not from the windowed task-record list
        "task_counts": c.task_manager.lifetime_counts() if hasattr(c, "task_manager") else {},
    }


# ----------------------------------------------------------------------
# session state dump (for the out-of-process CLI)
# ----------------------------------------------------------------------
def session_dir(pid: int | None = None) -> str:
    pid = pid or int(os.environ.get("RT_SESSION_PID", os.getpid()))
    return os.path.join("/tmp", "ray_tpu", f"session_{pid}")


def dump_state(client=None) -> str:
    """Write the current snapshot; returns the path."""
    c = client or _client()
    d = session_dir()
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, "state.json")
    tasks: dict = collections.defaultdict(lambda: collections.defaultdict(int))
    for t in c.cluster_info("tasks"):
        tasks[t.get("name", "?")][t.get("state", "?")] += 1
    snap = {
        "ts": time.time(),
        "pid": os.getpid(),
        "status": cluster_status(c),
        "tasks": {k: dict(v) for k, v in tasks.items()},
        "actors_list": c.cluster_info("actors"),
        "placement_groups": c.cluster_info("placement_groups"),
        "objects": c.cluster_info("objects"),
    }
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(snap, f, default=str)
    os.replace(tmp, path)
    return path


def dump_cluster_info(client) -> str:
    """Write the join credentials (agent listener address + authkeys) for
    out-of-process `rt agent` joins. 0600: the authkeys gate cluster entry
    (reference: redis password in `ray start --address`)."""
    d = session_dir()
    os.makedirs(d, exist_ok=True)
    path = os.path.join(d, "cluster_info.json")
    info = {
        "ts": time.time(),
        "pid": os.getpid(),
        "agent_address": list(client._agent_listener.address),
        "authkey": client._agent_listener.authkey.hex(),
        "transfer_authkey": client._transfer_authkey.hex(),
    }
    tmp = path + ".tmp"
    # 0600 from birth: the file holds cluster-entry authkeys
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "w") as f:
        json.dump(info, f)
    os.replace(tmp, path)
    return path


def load_latest_cluster_info() -> dict | None:
    """Newest cluster_info.json across live sessions (for `rt agent`)."""
    root = os.path.join("/tmp", "ray_tpu")
    best, best_ts = None, -1.0
    try:
        sessions = os.listdir(root)
    except FileNotFoundError:
        return None
    for s in sessions:
        p = os.path.join(root, s, "cluster_info.json")
        try:
            ts = os.path.getmtime(p)
        except OSError:
            continue
        if ts > best_ts:
            best, best_ts = p, ts
    if best is None:
        return None
    with open(best) as f:
        info = json.load(f)
    try:
        os.kill(info["pid"], 0)
    except (ProcessLookupError, PermissionError):
        return None  # head is gone
    return info


def load_latest_state() -> dict | None:
    """Newest state.json across sessions (CLI entry)."""
    root = os.path.join("/tmp", "ray_tpu")
    best, best_ts = None, -1.0
    try:
        sessions = os.listdir(root)
    except FileNotFoundError:
        return None
    for s in sessions:
        p = os.path.join(root, s, "state.json")
        try:
            ts = os.path.getmtime(p)
        except OSError:
            continue
        if ts > best_ts:
            best, best_ts = p, ts
    if best is None:
        return None
    with open(best) as f:
        return json.load(f)
