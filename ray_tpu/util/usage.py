"""Usage stats: local-only, opt-in telemetry.

Reference parity: python/ray/_common/usage/usage_lib.py — the reference
collects cluster metadata, library usage markers, and extra tags, writes
them to ``usage_stats.json`` in the session dir, and (when enabled)
reports them to a telemetry endpoint.

TPU-native/no-egress shape: collection is OPT-IN via
``RT_USAGE_STATS_ENABLED=1`` and the report NEVER leaves the machine —
``usage_stats.json`` lands in the session dir for operators who want a
machine-readable record of what ran (versions, cluster shape, which
libraries were imported). There is no phone-home code path at all; this
module exists so tooling built against the reference's usage schema has
a local equivalent to read.
"""

from __future__ import annotations

import json
import os
import platform
import sys
import threading
import time

SCHEMA_VERSION = "0.1"

_lock = threading.Lock()
_library_usages: set[str] = set()
_extra_tags: dict[str, str] = {}
_session_start_ms = int(time.time() * 1000)


def usage_stats_enabled() -> bool:
    """Disabled unless RT_USAGE_STATS_ENABLED=1 — the inverse of the
    reference's on-by-default posture, because there is no prompt flow
    here and silent collection is the wrong default for a library."""
    return os.environ.get("RT_USAGE_STATS_ENABLED", "0") == "1"


def record_library_usage(library: str):
    """Mark a library as used this session (reference:
    usage_lib.record_library_usage — called from lib __init__s)."""
    with _lock:
        _library_usages.add(str(library))


def record_extra_usage_tag(key: str, value: str):
    """Attach a custom key=value to the report (reference:
    usage_lib.record_extra_usage_tag / TagKey)."""
    with _lock:
        _extra_tags[str(key)] = str(value)


def _cluster_shape(client) -> dict:
    try:
        total = client.cluster_info("cluster_resources")
        nodes = client.cluster_info("nodes")
    except Exception:
        return {}
    return {
        "total_num_cpus": total.get("CPU"),
        "total_num_tpus": total.get("TPU"),
        "total_memory_gb": round(total.get("memory", 0) / (1 << 30), 2) or None,
        "total_num_nodes": len(nodes),
    }


def generate_report_data(client=None) -> dict:
    """Build the report dict (reference: usage_lib.generate_report_data,
    UsageStatsToReport fields — the locally-meaningful subset)."""
    import ray_tpu

    with _lock:
        libs = sorted(_library_usages)
        tags = dict(_extra_tags)
    data = {
        "schema_version": SCHEMA_VERSION,
        "source": "LOCAL",  # never reported anywhere
        "collect_timestamp_ms": int(time.time() * 1000),
        "session_start_timestamp_ms": _session_start_ms,
        "ray_tpu_version": getattr(ray_tpu, "__version__", "0.0.0"),
        "python_version": platform.python_version(),
        "os": sys.platform,
        "library_usages": libs,
        "extra_usage_tags": tags,
    }
    if client is not None:
        data.update(_cluster_shape(client))
    return data


def write_usage_stats(client=None, path: str | None = None) -> str | None:
    """Write usage_stats.json into the session dir (reference:
    UsageStatsToWrite / _write_usage_data). No-op unless enabled."""
    if not usage_stats_enabled():
        return None
    from ray_tpu.util.state import session_dir

    out = path or os.path.join(session_dir(), "usage_stats.json")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    tmp = out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(generate_report_data(client), f, indent=1)
    os.replace(tmp, out)
    return out
