"""multiprocessing.Pool drop-in over ray_tpu tasks.

Reference parity: python/ray/util/multiprocessing/pool.py — Pool with
apply/apply_async/map/map_async/starmap/imap/imap_unordered over cluster
tasks instead of local processes.
"""

from __future__ import annotations

import itertools

import ray_tpu


class AsyncResult:
    def __init__(self, refs, single: bool = False):
        self._refs = refs
        self._single = single

    def get(self, timeout: float | None = None):
        out = ray_tpu.get(self._refs, timeout=timeout)
        return out[0] if self._single else out

    def wait(self, timeout: float | None = None):
        ray_tpu.wait(self._refs, num_returns=len(self._refs), timeout=timeout)

    def ready(self) -> bool:
        ready, _ = ray_tpu.wait(self._refs, num_returns=len(self._refs), timeout=0)
        return len(ready) == len(self._refs)

    def successful(self) -> bool:
        if not self.ready():
            raise ValueError("result is not ready")  # multiprocessing contract
        try:
            ray_tpu.get(self._refs, timeout=0)
            return True
        except Exception:
            return False


class Pool:
    """Tasks are dispatched through one shared remote function; chunking
    matches multiprocessing semantics (chunksize items per task)."""

    def __init__(self, processes: int | None = None, initializer=None, initargs=()):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        self._processes = processes or int(ray_tpu.cluster_resources().get("CPU", 4))
        self._initializer = initializer
        self._initargs = initargs
        self._closed = False

        @ray_tpu.remote
        def _run_chunk(fn, chunk, star, init, initargs, pool_key):
            if init is not None:
                # once-per-worker-process semantics (stdlib runs the
                # initializer in each worker's startup, not per task)
                import builtins

                done = getattr(builtins, "_rt_pool_inits", None)
                if done is None:
                    done = builtins._rt_pool_inits = set()
                if pool_key not in done:
                    done.add(pool_key)
                    init(*initargs)
            return [fn(*args) if star else fn(args) for args in chunk]

        self._run_chunk = _run_chunk
        import uuid

        self._pool_key = uuid.uuid4().hex

    # -- helpers --
    def _check(self):
        if self._closed:
            raise ValueError("Pool not running")

    def _chunks(self, iterable, chunksize):
        it = iter(iterable)
        while True:
            chunk = list(itertools.islice(it, chunksize))
            if not chunk:
                return
            yield chunk

    def _submit(self, fn, iterable, chunksize, star):
        items = list(iterable)
        if chunksize is None:
            chunksize = max(1, len(items) // (self._processes * 4) or 1)
        return [
            self._run_chunk.remote(fn, chunk, star, self._initializer, self._initargs, self._pool_key)
            for chunk in self._chunks(items, chunksize)
        ], chunksize

    def _submit_lazy(self, fn, iterable, chunksize, star, max_inflight):
        """Generator of completed chunk refs with bounded in-flight chunks
        (keeps imap truly lazy over unbounded iterables)."""
        inflight: list = []
        for chunk in self._chunks(iterable, chunksize):
            inflight.append(
                self._run_chunk.remote(fn, chunk, star, self._initializer, self._initargs, self._pool_key)
            )
            while len(inflight) >= max_inflight:
                yield inflight.pop(0)
        yield from inflight

    # -- API --
    def apply(self, fn, args=(), kwds=None):
        return self.apply_async(fn, args, kwds).get()

    def apply_async(self, fn, args=(), kwds=None, callback=None, error_callback=None):
        self._check()
        kwds = kwds or {}

        @ray_tpu.remote
        def _apply(f, a, kw):
            return f(*a, **kw)

        res = AsyncResult([_apply.remote(fn, args, kwds)], single=True)
        if callback is not None or error_callback is not None:
            import threading

            def waiter():
                try:
                    out = res.get()
                except Exception as e:  # noqa: BLE001
                    if error_callback is not None:
                        error_callback(e)
                    return
                if callback is not None:
                    callback(out)

            threading.Thread(target=waiter, daemon=True).start()
        return res

    def map(self, fn, iterable, chunksize=None):
        return self.map_async(fn, iterable, chunksize).get()

    def map_async(self, fn, iterable, chunksize=None):
        self._check()
        refs, _ = self._submit(fn, [(x,) for x in iterable], chunksize, star=True)
        return _FlattenResult(refs)

    def starmap(self, fn, iterable, chunksize=None):
        return self.starmap_async(fn, iterable, chunksize).get()

    def starmap_async(self, fn, iterable, chunksize=None):
        self._check()
        refs, _ = self._submit(fn, iterable, chunksize, star=True)
        return _FlattenResult(refs)

    def imap(self, fn, iterable, chunksize=1):
        self._check()
        args = ((x,) for x in iterable)
        for ref in self._submit_lazy(fn, args, chunksize, star=True, max_inflight=self._processes * 2):
            yield from ray_tpu.get(ref)

    def imap_unordered(self, fn, iterable, chunksize=1):
        self._check()
        args = ((x,) for x in iterable)
        pending: list = []
        for ref in self._submit_lazy(fn, args, chunksize, star=True, max_inflight=self._processes * 2):
            pending.append(ref)
            ready, pending = ray_tpu.wait(pending, num_returns=1, timeout=0)
            for r in ready:
                yield from ray_tpu.get(r)
        while pending:
            ready, pending = ray_tpu.wait(pending, num_returns=1)
            yield from ray_tpu.get(ready[0])

    def close(self):
        self._closed = True

    def terminate(self):
        self._closed = True

    def join(self):
        if not self._closed:
            raise ValueError("Pool is still running")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()


class _FlattenResult(AsyncResult):
    def get(self, timeout: float | None = None):
        chunks = ray_tpu.get(self._refs, timeout=timeout)
        return [x for chunk in chunks for x in chunk]
