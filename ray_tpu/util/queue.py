"""Distributed Queue backed by an async actor.

Reference parity: python/ray/util/queue.py — Queue with put/get
(blocking with timeout), put_nowait/get_nowait, qsize/empty/full,
put_nowait_batch/get_nowait_batch, shutdown.
"""

from __future__ import annotations

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


@ray_tpu.remote(num_cpus=0, max_concurrency=16)
class _QueueActor:
    def __init__(self, maxsize: int):
        import asyncio

        self._maxsize = maxsize
        self._q = asyncio.Queue(maxsize)

    async def put(self, item, timeout: float | None = None):
        import asyncio

        try:
            await asyncio.wait_for(self._q.put(item), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def get(self, timeout: float | None = None):
        import asyncio

        try:
            return True, await asyncio.wait_for(self._q.get(), timeout)
        except asyncio.TimeoutError:
            return False, None

    async def put_nowait(self, item):
        if self._q.full():
            return False
        self._q.put_nowait(item)
        return True

    async def get_nowait(self):
        if self._q.empty():
            return False, None
        return True, self._q.get_nowait()

    async def put_nowait_batch(self, items):
        if self._maxsize > 0 and self._q.qsize() + len(items) > self._maxsize:
            return False
        for it in items:
            self._q.put_nowait(it)
        return True

    async def get_nowait_batch(self, n):
        if self._q.qsize() < n:
            return None
        return [self._q.get_nowait() for _ in range(n)]

    async def qsize(self):
        return self._q.qsize()

    async def empty(self):
        return self._q.empty()

    async def full(self):
        return self._q.full()


class Queue:
    def __init__(self, maxsize: int = 0, actor_options: dict | None = None):
        self.maxsize = maxsize
        self.actor = _QueueActor.options(**(actor_options or {})).remote(maxsize)

    def put(self, item, block: bool = True, timeout: float | None = None):
        if not block:
            return self.put_nowait(item)
        ok = ray_tpu.get(self.actor.put.remote(item, timeout))
        if not ok:
            raise Full("queue full")

    def get(self, block: bool = True, timeout: float | None = None):
        if not block:
            return self.get_nowait()
        ok, item = ray_tpu.get(self.actor.get.remote(timeout))
        if not ok:
            raise Empty("queue empty")
        return item

    def put_nowait(self, item):
        if not ray_tpu.get(self.actor.put_nowait.remote(item)):
            raise Full("queue full")

    def get_nowait(self):
        ok, item = ray_tpu.get(self.actor.get_nowait.remote())
        if not ok:
            raise Empty("queue empty")
        return item

    def put_nowait_batch(self, items):
        if not ray_tpu.get(self.actor.put_nowait_batch.remote(list(items))):
            raise Full("batch exceeds queue capacity")

    def get_nowait_batch(self, n: int):
        out = ray_tpu.get(self.actor.get_nowait_batch.remote(n))
        if out is None:
            raise Empty(f"fewer than {n} items queued")
        return out

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return ray_tpu.get(self.actor.empty.remote())

    def full(self) -> bool:
        return ray_tpu.get(self.actor.full.remote())

    def shutdown(self):
        ray_tpu.kill(self.actor)
