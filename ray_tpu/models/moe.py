"""Mixture-of-Experts Llama variant with expert parallelism over the `ep`
mesh axis.

The reference has no MoE/expert-parallel implementation of its own (it
passes engine args through to vLLM — SURVEY.md §2.5); here it is
first-class and TPU-native, GShard/Switch-style:

- top-k router with capacity-based token dropping, built from one-hot
  matmuls and cumulative sums — every shape static, everything lowers to
  MXU einsums (no gather/scatter, no ragged shapes).
- expert weights carry a leading `E` dim with logical axis "expert" -> ep
  (parallel/mesh.py ShardingRules), so GSPMD shards experts across chips
  and inserts the dispatch/return all-to-alls on ICI automatically from
  the einsum operands' shardings.
- grouped dispatch: tokens are dispatched per group (dim G below) so the
  [G, S, E, C] dispatch tensor stays small; groups ride the batch (dp)
  sharding.
- aux losses per Switch Transformer: load-balance (fraction-routed x
  fraction-probability) and router z-loss, both returned from loss_fn.

Layer stack: same GQA attention blocks as models/llama.py; the dense
SwiGLU MLP is replaced by the MoE block every `moe_every` layers.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

from ray_tpu.models.llama import LlamaConfig, _attention_block
from ray_tpu.ops.layers import cross_entropy_loss, rms_norm, rotary_embedding


@dataclass(frozen=True)
class MoEConfig(LlamaConfig):
    num_experts: int = 8
    experts_per_token: int = 2  # top-k
    capacity_factor: float = 1.25
    router_z_coeff: float = 1e-3
    balance_coeff: float = 1e-2

    @staticmethod
    def tiny(**kw):
        base = dict(
            vocab_size=512, hidden_size=128, intermediate_size=256, num_layers=2,
            num_heads=4, num_kv_heads=2, max_seq_len=256, num_experts=4, experts_per_token=2,
        )
        return MoEConfig(**{**base, **kw})


def param_logical_axes(config: MoEConfig):
    return {
        "embed": ("vocab", "embed"),
        "unembed": ("embed", "vocab"),
        "final_norm": (None,),
        "layers": {
            "wq": (None, "embed", "heads"),
            "wk": (None, "embed", "kv_heads"),
            "wv": (None, "embed", "kv_heads"),
            "wo": (None, "heads", "embed"),
            "attn_norm": (None, None),
            "mlp_norm": (None, None),
            "w_router": (None, "embed", "expert"),
            "we_gate": (None, "expert", "embed", "mlp"),
            "we_up": (None, "expert", "embed", "mlp"),
            "we_down": (None, "expert", "mlp", "embed"),
        },
    }


def init_params(config: MoEConfig, key) -> dict:
    h, hd, dt = config.hidden_size, config.hd, jnp.dtype(config.dtype)
    L, E, I = config.num_layers, config.num_experts, config.intermediate_size
    keys = jax.random.split(key, 12)

    def dense(k, *shape, fan_in):
        return (jax.random.normal(k, shape, dtype=jnp.float32) * (fan_in**-0.5)).astype(dt)

    return {
        "embed": dense(keys[0], config.vocab_size, h, fan_in=h),
        "unembed": dense(keys[1], h, config.vocab_size, fan_in=h),
        "final_norm": jnp.ones((h,), dt),
        "layers": {
            "wq": dense(keys[2], L, h, config.num_heads * hd, fan_in=h),
            "wk": dense(keys[3], L, h, config.num_kv_heads * hd, fan_in=h),
            "wv": dense(keys[4], L, h, config.num_kv_heads * hd, fan_in=h),
            "wo": dense(keys[5], L, config.num_heads * hd, h, fan_in=config.num_heads * hd),
            "attn_norm": jnp.ones((L, h), dt),
            "mlp_norm": jnp.ones((L, h), dt),
            # router stays f32: tiny, and routing decisions are precision-
            # sensitive (Switch Transformer recipe)
            "w_router": jax.random.normal(keys[6], (L, h, E), jnp.float32) * (h**-0.5),
            "we_gate": dense(keys[7], L, E, h, I, fan_in=h),
            "we_up": dense(keys[8], L, E, h, I, fan_in=h),
            "we_down": dense(keys[9], L, E, I, h, fan_in=I),
        },
    }


def _top_k_dispatch(probs, k: int, capacity: int):
    """probs: [G, S, E] router probabilities. Returns (dispatch [G,S,E,C]
    bool-ish f32, combine [G,S,E,C] f32, aux dict).

    Choices are made greedily (choice 0 = argmax, then masked re-argmax),
    each choice claims a slot via a token-order cumsum within its expert;
    tokens past `capacity` are dropped (their combine weight is 0 — the
    residual connection carries them through unchanged).
    """
    G, S, E = probs.shape
    remaining = probs
    counts = jnp.zeros((G, 1, E), probs.dtype)  # slots claimed so far per expert
    dispatch = jnp.zeros((G, S, E, capacity), probs.dtype)
    combine = jnp.zeros((G, S, E, capacity), probs.dtype)
    frac_routed = jnp.zeros((G, E), probs.dtype)

    for _ in range(k):
        idx = jnp.argmax(remaining, axis=-1)  # [G, S]
        onehot = jax.nn.one_hot(idx, E, dtype=probs.dtype)  # [G, S, E]
        gate = jnp.sum(probs * onehot, axis=-1)  # [G, S]
        # position of each token in its chosen expert's queue (token order)
        pos = jnp.cumsum(onehot, axis=1) - onehot + counts  # [G, S, E]
        pos_tok = jnp.sum(pos * onehot, axis=-1)  # [G, S]
        keep = pos_tok < capacity
        pos_oh = jax.nn.one_hot(pos_tok, capacity, dtype=probs.dtype)  # [G, S, C]
        slot = onehot[..., None] * pos_oh[:, :, None, :]  # [G, S, E, C]
        slot = slot * keep[:, :, None, None]
        dispatch = dispatch + slot
        combine = combine + slot * gate[:, :, None, None]
        counts = counts + jnp.sum(onehot * keep[..., None], axis=1, keepdims=True)
        frac_routed = frac_routed + jnp.mean(onehot, axis=1)
        remaining = remaining * (1.0 - onehot)  # mask chosen expert for next choice

    # normalize combine gates over the k chosen experts (top-k softmax mass)
    denom = jnp.sum(combine, axis=(2, 3), keepdims=True)
    combine = combine / jnp.maximum(denom, 1e-9)
    aux = {"frac_routed": frac_routed / k}
    return dispatch, combine, aux


def moe_block(x, layer, config: MoEConfig):
    """x: [B, T, H] -> [B, T, H]; returns (out, aux_losses [2])."""
    B, T, H = x.shape
    E, k = config.num_experts, config.experts_per_token
    xn = rms_norm(x, layer["mlp_norm"], config.rms_eps)
    # groups = batch rows: dispatch tensors stay [B, T, E, C] and ride the
    # existing dp/fsdp batch sharding
    logits = jnp.einsum("gsh,he->gse", xn.astype(jnp.float32), layer["w_router"])
    probs = jax.nn.softmax(logits, axis=-1)
    capacity = max(1, int(config.capacity_factor * k * T / E))
    dispatch, combine, aux = _top_k_dispatch(probs, k, capacity)

    # dispatch tokens to expert buffers: [G, E, C, H] (ep-sharded on E)
    xe = jnp.einsum("gsec,gsh->gech", dispatch.astype(xn.dtype), xn)
    g = jnp.einsum("gech,ehi->geci", xe, layer["we_gate"])
    u = jnp.einsum("gech,ehi->geci", xe, layer["we_up"])
    ye = jnp.einsum("geci,eih->gech", jax.nn.silu(g) * u, layer["we_down"])
    y = jnp.einsum("gsec,gech->gsh", combine.astype(ye.dtype), ye)

    # Switch aux losses: balance = E * sum_e f_e * p_e ; z = mean(lse^2)
    frac_prob = jnp.mean(probs, axis=1)  # [G, E]
    balance = E * jnp.mean(jnp.sum(aux["frac_routed"] * frac_prob, axis=-1))
    z = jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2)
    return x + y, jnp.stack([balance, z])


def _layer_fn(x, layer, config: MoEConfig, cos, sin, positions, mesh=None):
    x = _attention_block(x, layer, config, cos, sin, positions, mesh=mesh)
    x, aux = moe_block(x, layer, config)
    return x, aux


def forward(params, tokens, config: MoEConfig, positions=None, mesh=None):
    """tokens [B, T] -> (logits [B, T, V], aux_losses [2])."""
    B, T = tokens.shape
    if positions is None:
        positions = jnp.arange(T, dtype=jnp.int32)
    cos, sin = rotary_embedding(positions, config.hd, config.rope_theta, dtype=jnp.float32)
    x = jnp.take(params["embed"], tokens, axis=0)

    layer_fn = partial(_layer_fn, config=config, cos=cos, sin=sin, positions=positions, mesh=mesh)
    if config.remat:
        policy = getattr(jax.checkpoint_policies, config.remat_policy)
        layer_fn = jax.checkpoint(layer_fn, policy=policy)

    if config.scan_layers:
        def body(carry, layer):
            out, aux = layer_fn(carry, layer)
            return out, aux

        x, auxs = jax.lax.scan(body, x, params["layers"])
        aux = jnp.sum(auxs, axis=0)
    else:
        aux = jnp.zeros((2,))
        for i in range(config.num_layers):
            layer = jax.tree.map(lambda p: p[i], params["layers"])
            x, a = layer_fn(x, layer)
            aux = aux + a

    x = rms_norm(x, params["final_norm"], config.rms_eps)
    return jnp.dot(x, params["unembed"], preferred_element_type=jnp.float32), aux


def loss_fn(params, batch, config: MoEConfig, mesh=None):
    logits, aux = forward(params, batch["tokens"], config, mesh=mesh)
    ce = cross_entropy_loss(logits, batch["targets"])
    return ce + config.balance_coeff * aux[0] + config.router_z_coeff * aux[1]
