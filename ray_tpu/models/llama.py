"""Llama-family transformer, TPU-native.

Functional pytree implementation (no framework classes on the hot path):
- layers stacked into single arrays and iterated with `lax.scan` (one XLA
  compilation of one layer; constant compile time in depth)
- `jax.checkpoint` per layer (rematerialization trades FLOPs for HBM)
- GQA + RoPE + SwiGLU + RMSNorm (Llama-2/3 architecture)
- every parameter carries a logical-axes annotation consumed by
  ray_tpu.parallel.mesh.ShardingRules, lowering DP/FSDP/TP/SP configs to
  GSPMD NamedShardings (the TPU-native equivalent of the reference's
  DDP/FSDP wrapping in train/torch/train_loop_utils.py:153,374 and vLLM
  tensor_parallel_size pass-through in vllm_models.py:215)

KV-cache decode path for serving lives in ray_tpu.llm.engine.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp

from ray_tpu.ops.flash_attention import flash_attention
from ray_tpu.ops.layers import apply_rope, cross_entropy_loss, rms_norm, rotary_embedding


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    head_dim: int | None = None
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    rms_eps: float = 1e-5
    dtype: str = "bfloat16"
    remat: bool = True
    # jax.checkpoint_policies name: "nothing_saveable" recomputes the whole
    # layer in backward (min HBM); "dots_with_no_batch_dims_saveable" keeps
    # matmul outputs (fewer recompute FLOPs when HBM allows)
    remat_policy: str = "nothing_saveable"
    scan_layers: bool = True
    attention_impl: str = "auto"  # auto | pallas | xla
    tie_embeddings: bool = False

    @property
    def hd(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    @staticmethod
    def llama2_7b(**kw):
        return LlamaConfig(**{**dict(vocab_size=32000, hidden_size=4096, intermediate_size=11008, num_layers=32, num_heads=32, num_kv_heads=32), **kw})

    @staticmethod
    def llama3_8b(**kw):
        return LlamaConfig(**{**dict(vocab_size=128256, hidden_size=4096, intermediate_size=14336, num_layers=32, num_heads=32, num_kv_heads=8, rope_theta=500000.0), **kw})

    @staticmethod
    def tiny(**kw):
        return LlamaConfig(**{**dict(vocab_size=512, hidden_size=128, intermediate_size=256, num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=256), **kw})

    def num_params(self) -> int:
        h, i, v, L = self.hidden_size, self.intermediate_size, self.vocab_size, self.num_layers
        hd = self.hd
        attn = h * (self.num_heads * hd) + 2 * h * (self.num_kv_heads * hd) + (self.num_heads * hd) * h
        mlp = 3 * h * i
        return L * (attn + mlp + 2 * h) + v * h * (1 if self.tie_embeddings else 2) + h


# logical axes per parameter (leaf name -> tuple of logical dims);
# layer-stacked params get a leading "layers" (unsharded) axis
PARAM_AXES = {
    "embed": ("vocab", "embed"),
    "unembed": ("embed", "vocab"),
    "final_norm": (None,),
    "layers": {
        "wq": (None, "embed", "heads"),
        "wk": (None, "embed", "kv_heads"),
        "wv": (None, "embed", "kv_heads"),
        "wo": (None, "heads", "embed"),
        "w_gate": (None, "embed", "mlp"),
        "w_up": (None, "embed", "mlp"),
        "w_down": (None, "mlp", "embed"),
        "attn_norm": (None, None),
        "mlp_norm": (None, None),
    },
}


def param_logical_axes(config: LlamaConfig):
    axes = {
        "embed": PARAM_AXES["embed"],
        "final_norm": PARAM_AXES["final_norm"],
        "layers": dict(PARAM_AXES["layers"]),
    }
    if not config.tie_embeddings:
        axes["unembed"] = PARAM_AXES["unembed"]
    return axes


def init_params(config: LlamaConfig, key) -> dict:
    h = config.hidden_size
    hd = config.hd
    dt = jnp.dtype(config.dtype)
    L = config.num_layers
    keys = jax.random.split(key, 10)

    def norm_init(*shape):
        return jnp.ones(shape, dtype=dt)

    def dense_init(k, *shape, fan_in):
        return (jax.random.normal(k, shape, dtype=jnp.float32) * (fan_in**-0.5)).astype(dt)

    params = {
        "embed": dense_init(keys[0], config.vocab_size, h, fan_in=h),
        "final_norm": norm_init(h),
        "layers": {
            "wq": dense_init(keys[1], L, h, config.num_heads * hd, fan_in=h),
            "wk": dense_init(keys[2], L, h, config.num_kv_heads * hd, fan_in=h),
            "wv": dense_init(keys[3], L, h, config.num_kv_heads * hd, fan_in=h),
            "wo": dense_init(keys[4], L, config.num_heads * hd, h, fan_in=config.num_heads * hd),
            "w_gate": dense_init(keys[5], L, h, config.intermediate_size, fan_in=h),
            "w_up": dense_init(keys[6], L, h, config.intermediate_size, fan_in=h),
            "w_down": dense_init(keys[7], L, config.intermediate_size, h, fan_in=config.intermediate_size),
            "attn_norm": norm_init(L, h),
            "mlp_norm": norm_init(L, h),
        },
    }
    if not config.tie_embeddings:
        params["unembed"] = dense_init(keys[8], h, config.vocab_size, fan_in=h)
    return params


def _attention_block(x, layer, config: LlamaConfig, cos, sin, positions, mesh=None):
    B, T, H = x.shape
    nh, nkv, hd = config.num_heads, config.num_kv_heads, config.hd
    xn = rms_norm(x, layer["attn_norm"], config.rms_eps)
    q = jnp.dot(xn, layer["wq"]).reshape(B, T, nh, hd).transpose(0, 2, 1, 3)
    k = jnp.dot(xn, layer["wk"]).reshape(B, T, nkv, hd).transpose(0, 2, 1, 3)
    v = jnp.dot(xn, layer["wv"]).reshape(B, T, nkv, hd).transpose(0, 2, 1, 3)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    if mesh is not None and "sp" in mesh.axis_names:
        # sequence parallelism: ring attention over the sp axis (shard_map
        # + ppermute on ICI; ray_tpu/parallel/ring_attention.py)
        from ray_tpu.parallel.ring_attention import sp_attention

        rep = nh // nkv
        if rep > 1:
            k = jnp.repeat(k, rep, axis=1)
            v = jnp.repeat(v, rep, axis=1)
        o = sp_attention(q, k, v, mesh, impl="ring", causal=True)
    else:
        o = flash_attention(q, k, v, True, None, config.attention_impl)
    o = o.transpose(0, 2, 1, 3).reshape(B, T, nh * hd)
    return x + jnp.dot(o, layer["wo"])


def _mlp_block(x, layer, config: LlamaConfig):
    xn = rms_norm(x, layer["mlp_norm"], config.rms_eps)
    g = jnp.dot(xn, layer["w_gate"])
    u = jnp.dot(xn, layer["w_up"])
    return x + jnp.dot(jax.nn.silu(g) * u, layer["w_down"])


def _layer_fn(x, layer, config: LlamaConfig, cos, sin, positions, mesh=None):
    x = _attention_block(x, layer, config, cos, sin, positions, mesh=mesh)
    x = _mlp_block(x, layer, config)
    return x


def forward(params: dict, tokens, config: LlamaConfig, positions=None, mesh=None):
    """tokens: [B, T] int32 -> logits [B, T, vocab]."""
    B, T = tokens.shape
    if positions is None:
        positions = jnp.arange(T, dtype=jnp.int32)
    cos, sin = rotary_embedding(positions, config.hd, config.rope_theta, dtype=jnp.float32)
    x = jnp.take(params["embed"], tokens, axis=0)

    layer_fn = partial(_layer_fn, config=config, cos=cos, sin=sin, positions=positions, mesh=mesh)
    if config.remat:
        policy = getattr(jax.checkpoint_policies, config.remat_policy)
        layer_fn = jax.checkpoint(layer_fn, policy=policy)

    if config.scan_layers:
        def scan_body(carry, layer):
            return layer_fn(carry, layer), None

        x, _ = jax.lax.scan(scan_body, x, params["layers"])
    else:
        L = config.num_layers
        for i in range(L):
            layer = jax.tree.map(lambda p: p[i], params["layers"])
            x = layer_fn(x, layer)

    x = rms_norm(x, params["final_norm"], config.rms_eps)
    unembed = params["embed"].T if config.tie_embeddings else params["unembed"]
    return jnp.dot(x, unembed, preferred_element_type=jnp.float32)


def loss_fn(params, batch, config: LlamaConfig, mesh=None):
    """batch: {tokens [B,T], targets [B,T] (-100 = ignore)} -> scalar loss."""
    logits = forward(params, batch["tokens"], config, mesh=mesh)
    return cross_entropy_loss(logits, batch["targets"])


def flops_per_token(config: LlamaConfig, seq_len: int | None = None) -> float:
    """Training FLOPs/token ≈ 6N + attention quadratic term."""
    n = config.num_params()
    f = 6.0 * n
    if seq_len:
        # 12 * L * H * T * hd per token (fwd+bwd attention scores+values)
        f += 12.0 * config.num_layers * config.num_heads * seq_len * config.hd
    return f
