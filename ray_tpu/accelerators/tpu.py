"""TPU accelerator manager: detection, topology model, chip isolation.

Reference parity: python/ray/_private/accelerators/tpu.py —
TPUAcceleratorManager (:267): chip detection via /dev/accel* or /dev/vfio
(:294-313), resource name "TPU" (:271), valid chip counts {1,2,4,8} (:17,363),
TPU_VISIBLE_CHIPS + TPU_CHIPS_PER_HOST_BOUNDS/TPU_HOST_BOUNDS sub-host
isolation (:377-417), GKE env / GCE metadata pod discovery (:420-527), slice
resources {tpu_name: 1} on every slice worker + "TPU-{pod}-head" on worker 0
(:576-639), node labels ray.io/tpu-* (:641-672), type/topology tables v2-v6e
(:65,88-102) and chips-per-host rules (:135-148,184-210).
"""

from __future__ import annotations

import glob
import logging
import os

logger = logging.getLogger(__name__)

RESOURCE_NAME = "TPU"
NUM_TPUS_PER_HOST_DEFAULT = 4
VALID_CHIP_COUNTS = (1, 2, 4, 8)

# generation -> cores per chip (v4/v5p have 2 cores/chip megacore'd; v5e/v6e 1)
GENERATION_CORES_PER_CHIP = {
    "v2": 2,
    "v3": 2,
    "v4": 2,
    "v5p": 2,
    "v5litepod": 1,
    "v5e": 1,
    "v6e": 1,
}

# accelerator type -> list of valid topology strings (subset; reference
# tpu.py:88-102 keeps similar tables)
VALID_TOPOLOGIES = {
    "v2": {"2x2", "4x4", "4x8", "8x8", "8x16", "16x16"},
    "v3": {"2x2", "4x4", "4x8", "8x8", "8x16", "16x16", "16x32", "32x32"},
    "v4": {"2x2x1", "2x2x2", "2x2x4", "2x4x4", "4x4x4", "4x4x8", "4x8x8", "8x8x8", "8x8x16"},
    "v5p": {"2x2x1", "2x2x2", "2x2x4", "2x4x4", "4x4x4", "4x4x8", "4x8x8", "8x8x8", "8x16x16"},
    "v5litepod": {"1x1", "2x2", "2x4", "4x4", "4x8", "8x8", "8x16", "16x16"},
    "v6e": {"1x1", "2x2", "2x4", "4x4", "4x8", "8x8", "8x16", "16x16"},
}


def _chips_from_topology(topology: str) -> int:
    n = 1
    for part in topology.lower().split("x"):
        n *= int(part)
    return n


def pod_type_chip_count(pod_type: str) -> int:
    """'v5litepod-64' -> 64 cores -> chips depend on generation."""
    gen, _, cores = pod_type.partition("-")
    cores = int(cores)
    cpc = GENERATION_CORES_PER_CHIP.get(gen, 1)
    return max(cores // cpc, 1)


def chips_per_host(pod_type: str, topology: str | None = None) -> int:
    """Hosts have 4 chips except single-host slices and 8-chip v5e/v6e hosts
    (reference rules: tpu.py:135-148,184-210)."""
    gen = pod_type.partition("-")[0]
    total = pod_type_chip_count(pod_type)
    if total <= 4:
        return total
    if gen in ("v5litepod", "v6e") and total == 8:
        return 8
    return NUM_TPUS_PER_HOST_DEFAULT


def num_hosts(pod_type: str, topology: str | None = None) -> int:
    total = pod_type_chip_count(pod_type)
    return max(total // chips_per_host(pod_type, topology), 1)


class TPUAcceleratorManager:
    """Per-node TPU detection + worker-env isolation."""

    @staticmethod
    def get_resource_name() -> str:
        return RESOURCE_NAME

    @staticmethod
    def get_current_node_num_accelerators() -> int:
        env = os.environ.get("RT_NUM_TPUS")
        if env is not None:
            return int(env)
        n = len(glob.glob("/dev/accel*"))
        if n == 0:
            n = len(glob.glob("/dev/vfio/[0-9]*"))
        return n

    @staticmethod
    def get_current_node_accelerator_type() -> str | None:
        # GKE sets these; GCE metadata would be queried on real TPU VMs
        accel = os.environ.get("TPU_ACCELERATOR_TYPE")
        if accel:
            return accel
        return None

    @staticmethod
    def validate_resource_request_quantity(quantity: float) -> tuple[bool, str | None]:
        if quantity not in VALID_CHIP_COUNTS:
            return (
                False,
                f"TPU request must be one of {VALID_CHIP_COUNTS} (got {quantity}): "
                "sub-host slices must align to chip-bounds",
            )
        return True, None

    @classmethod
    def set_current_process_visible_accelerators(cls, chip_ids: list):
        """Isolation env for the current process (reference: tpu.py:377-417)."""
        os.environ.update(cls.worker_env_for_chips(chip_ids))

    @staticmethod
    def worker_env_for_chips(chip_ids: list[int]) -> dict:
        n = len(chip_ids)
        env = {"TPU_VISIBLE_CHIPS": ",".join(str(c) for c in chip_ids)}
        if n == 1:
            env["TPU_CHIPS_PER_HOST_BOUNDS"] = "1,1,1"
            env["TPU_HOST_BOUNDS"] = "1,1,1"
        elif n == 2:
            env["TPU_CHIPS_PER_HOST_BOUNDS"] = "1,2,1"
            env["TPU_HOST_BOUNDS"] = "1,1,1"
        elif n == 4:
            env["TPU_CHIPS_PER_HOST_BOUNDS"] = "2,2,1"
            env["TPU_HOST_BOUNDS"] = "1,1,1"
        return env

    # ---- slice discovery (env-driven; GCE metadata on real pods) ----
    @staticmethod
    def get_current_node_tpu_pod_type() -> str | None:
        accel = os.environ.get("TPU_ACCELERATOR_TYPE")  # e.g. "v5litepod-16"
        return accel

    @staticmethod
    def get_current_node_tpu_name() -> str | None:
        return os.environ.get("TPU_NAME")

    @staticmethod
    def get_current_node_tpu_worker_id() -> int | None:
        wid = os.environ.get("TPU_WORKER_ID")
        return int(wid) if wid is not None else None

    @staticmethod
    def get_current_node_tpu_topology() -> str | None:
        return os.environ.get("TPU_TOPOLOGY")

    @classmethod
    def get_current_node_additional_resources(cls) -> dict:
        """Per-slice gang-scheduling resources (reference: tpu.py:576-639)."""
        out = {}
        name = cls.get_current_node_tpu_name()
        pod = cls.get_current_node_tpu_pod_type()
        wid = cls.get_current_node_tpu_worker_id()
        if name:
            out[name] = 1.0
        if pod and wid == 0:
            out[f"TPU-{pod}-head"] = 1.0
        return out

    @classmethod
    def get_current_node_labels(cls) -> dict:
        out = {}
        name = cls.get_current_node_tpu_name()
        if name:
            out["ray_tpu.io/tpu-slice-name"] = name
        wid = cls.get_current_node_tpu_worker_id()
        if wid is not None:
            out["ray_tpu.io/tpu-worker-id"] = str(wid)
        topo = cls.get_current_node_tpu_topology()
        if topo:
            out["ray_tpu.io/tpu-topology"] = topo
        pod = cls.get_current_node_tpu_pod_type()
        if pod:
            out["ray_tpu.io/tpu-pod-type"] = pod
        return out
