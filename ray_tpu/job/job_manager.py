"""Job submission: run driver entrypoints as supervised subprocesses.

Reference parity: python/ray/dashboard/modules/job/job_manager.py (submit
-> supervisor -> driver subprocess; status via GCS KV; log files per job)
+ job_submission.JobSubmissionClient's API shape. Collapsed for the
single-host control plane: the supervisor is a thread in the head process,
drivers are real subprocesses with captured logs under the session dir.

    client = JobSubmissionClient()          # in a driver with init() done
    job_id = client.submit_job(entrypoint="python train.py",
                               runtime_env={"env_vars": {...}})
    client.get_job_status(job_id)           # PENDING/RUNNING/SUCCEEDED/...
    client.get_job_logs(job_id)
"""

from __future__ import annotations

import os
import subprocess
import threading
import time
import uuid
from dataclasses import asdict, dataclass, field

def _session_dir() -> str:
    from ray_tpu.util.state import session_dir

    d = session_dir()
    os.makedirs(os.path.join(d, "jobs"), exist_ok=True)
    return d


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = (SUCCEEDED, FAILED, STOPPED)


@dataclass
class JobInfo:
    job_id: str
    entrypoint: str
    status: str = JobStatus.PENDING
    submission_time: float = field(default_factory=time.time)
    start_time: float | None = None
    end_time: float | None = None
    returncode: int | None = None
    message: str = ""
    metadata: dict = field(default_factory=dict)
    log_path: str = ""


class JobManager:
    """Supervises driver subprocesses; state mirrors into the GCS KV so
    `list_jobs` works from any client of the same head."""

    def __init__(self, client=None):
        from ray_tpu.core import context

        self._client = client or context.get_client()
        self._jobs: dict[str, JobInfo] = {}
        self._procs: dict[str, subprocess.Popen] = {}
        self._lock = threading.Lock()

    def _kv_put(self, info: JobInfo):
        try:
            self._client.kv("put", key=f"job::{info.job_id}", value=asdict(info), namespace="_jobs")
        except Exception:
            pass

    def submit_job(
        self,
        *,
        entrypoint: str,
        runtime_env: dict | None = None,
        submission_id: str | None = None,
        metadata: dict | None = None,
    ) -> str:
        job_id = submission_id or f"raysubmit_{uuid.uuid4().hex[:16]}"
        log_path = os.path.join(_session_dir(), "jobs", f"{job_id}.log")
        info = JobInfo(job_id=job_id, entrypoint=entrypoint, metadata=metadata or {}, log_path=log_path)
        with self._lock:
            if job_id in self._jobs:
                raise ValueError(f"job {job_id} already exists")
            self._jobs[job_id] = info
        self._kv_put(info)

        env = dict(os.environ)
        renv = runtime_env or {}
        env.update({str(k): str(v) for k, v in (renv.get("env_vars") or {}).items()})
        env["RT_JOB_SUBMISSION_ID"] = job_id
        # export the attach credentials so the entrypoint's plain
        # ray_tpu.init() joins THIS cluster as a driver instead of booting
        # a private head (reference: job supervisor sets RAY_ADDRESS)
        try:
            import json as _json

            with open(os.path.join(_session_dir(), "cluster_info.json")) as f:
                ci = _json.load(f)
            env["RT_HEAD_ADDRESS"] = f"{ci['agent_address'][0]}:{ci['agent_address'][1]}"
            env["RT_HEAD_AUTHKEY"] = ci["authkey"]
        except Exception:
            pass  # local_mode / no listener: jobs run self-contained
        cwd = renv.get("working_dir") if renv.get("working_dir") and os.path.isdir(renv["working_dir"]) else None

        def run():
            logf = open(log_path, "wb")
            try:
                proc = subprocess.Popen(
                    entrypoint,
                    shell=True,
                    stdout=logf,
                    stderr=subprocess.STDOUT,
                    env=env,
                    cwd=cwd,
                    start_new_session=True,  # stop_job kills the whole group
                )
            except Exception as e:  # noqa: BLE001
                with self._lock:
                    info.status = JobStatus.FAILED
                    info.end_time = time.time()
                    info.message = f"failed to launch: {e}"
                self._kv_put(info)
                logf.close()
                return
            with self._lock:
                self._procs[job_id] = proc
                info.status = JobStatus.RUNNING
                info.start_time = time.time()
            self._kv_put(info)
            rc = proc.wait()
            logf.close()
            with self._lock:
                info.returncode = rc
                info.end_time = time.time()
                if info.status != JobStatus.STOPPED:
                    info.status = JobStatus.SUCCEEDED if rc == 0 else JobStatus.FAILED
                    info.message = "" if rc == 0 else f"driver exited with code {rc}"
                self._procs.pop(job_id, None)
            self._kv_put(info)

        threading.Thread(target=run, daemon=True, name=f"rt-job-{job_id[:18]}").start()
        return job_id

    def stop_job(self, job_id: str) -> bool:
        import signal

        with self._lock:
            info = self._jobs.get(job_id)
            proc = self._procs.get(job_id)
            if info is None or proc is None or info.status in JobStatus.TERMINAL:
                return False
            info.status = JobStatus.STOPPED
            info.message = "stopped by user"
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
        except Exception:
            try:
                proc.terminate()
            except Exception:
                pass
        self._kv_put(info)
        return True

    def get_job_info(self, job_id: str) -> JobInfo:
        with self._lock:
            info = self._jobs.get(job_id)
        if info is None:
            raise ValueError(f"no such job {job_id}")
        return info

    def get_job_status(self, job_id: str) -> str:
        return self.get_job_info(job_id).status

    def get_job_logs(self, job_id: str) -> str:
        info = self.get_job_info(job_id)
        try:
            with open(info.log_path, "rb") as f:
                return f.read().decode(errors="replace")
        except FileNotFoundError:
            return ""

    def tail_job_logs(self, job_id: str, poll_s: float = 0.2):
        """Generator of log chunks until the job reaches a terminal state."""
        info = self.get_job_info(job_id)
        pos = 0
        while True:
            try:
                with open(info.log_path, "rb") as f:
                    f.seek(pos)
                    chunk = f.read()
                    pos = f.tell()
            except FileNotFoundError:
                chunk = b""
            if chunk:
                yield chunk.decode(errors="replace")
            if info.status in JobStatus.TERMINAL:
                return
            time.sleep(poll_s)

    def list_jobs(self) -> list[JobInfo]:
        with self._lock:
            return list(self._jobs.values())

    def wait_until_finished(self, job_id: str, timeout: float | None = None) -> str:
        deadline = None if timeout is None else time.time() + timeout
        while True:
            st = self.get_job_status(job_id)
            if st in JobStatus.TERMINAL:
                return st
            if deadline is not None and time.time() > deadline:
                raise TimeoutError(f"job {job_id} still {st}")
            time.sleep(0.1)


_default_manager: JobManager | None = None


class JobSubmissionClient:
    """API-shape parity with ray.job_submission.JobSubmissionClient."""

    def __init__(self, address: str | None = None):
        global _default_manager
        if _default_manager is None:
            _default_manager = JobManager()
        self._mgr = _default_manager

    def submit_job(self, **kw) -> str:
        return self._mgr.submit_job(**kw)

    def stop_job(self, job_id: str) -> bool:
        return self._mgr.stop_job(job_id)

    def get_job_status(self, job_id: str) -> str:
        return self._mgr.get_job_status(job_id)

    def get_job_info(self, job_id: str) -> JobInfo:
        return self._mgr.get_job_info(job_id)

    def get_job_logs(self, job_id: str) -> str:
        return self._mgr.get_job_logs(job_id)

    def tail_job_logs(self, job_id: str):
        return self._mgr.tail_job_logs(job_id)

    def list_jobs(self) -> list[JobInfo]:
        return self._mgr.list_jobs()
